"""Checkpointable FILES-mode input: deterministic index-based sampling.

The reference's FILES/TENSORFLOW input mode built tf.data pipelines whose
iterator state tf.train.Checkpoint could snapshot, so a preempted worker
resumed mid-epoch instead of replaying or skipping data (reference
examples/mnist/keras/mnist_tf_ds.py builds such a pipeline;
TFNode.DataFeed's feed mode had no such story). This module is that
capability, designed TPU-first rather than as a stream wrapper:

- ``RecordIndex``: per-file record offsets (one cheap header-skip scan,
  cached in a ``.tosidx`` sidecar) make TFRecord files random-access.
- ``IndexedTFRecordDataset``: a global ``[0, N)`` index space over a file
  shard with ``record(i)`` random access.
- ``permute_index``: a 4-round Feistel cipher over the index domain — a
  seeded bijection computed in O(1) memory per lookup, so a *global*
  shuffle (not a buffer-local approximation like ``readers.shuffled``)
  needs no materialized permutation no matter how large the dataset.
- ``CheckpointableInput``: batches from the permuted index stream; the
  ENTIRE iterator state is one integer position (plus the config that
  derives everything else), so it snapshots into a checkpoint as a tiny
  JSON dict and resume is exact: the restored iterator yields precisely
  the batches the uninterrupted run would have.

Epoch ordering differs per epoch (the cipher key folds the epoch in), and
sharding happens in *sample space* (worker w of W takes positions
``w::W`` of the permuted stream), so every record is visited exactly once
per epoch across the cluster while workers stay embarrassingly parallel.
"""

import logging
import os
import struct
from typing import Iterator, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_IDX_MAGIC = b"TOSIDX2\n"
_IDX_SUFFIX = ".tosidx"


# ---------------------------------------------------------------------------
# Record index
# ---------------------------------------------------------------------------


def _scan_offsets(path: str) -> np.ndarray:
  """One pass over a TFRecord file reading only the 12-byte headers.

  TFRecord framing: [len u64][len_crc u32][payload len][payload_crc u32]
  (see data/tfrecord.py for the write side). Payload bytes are skipped
  with seek, so indexing cost is per-record, not per-byte.
  """
  from tensorflowonspark_tpu.data import fs
  offsets = []
  with fs.open_file(path, "rb") as f:
    pos = 0
    while True:
      header = f.read(12)
      if not header:
        break
      if len(header) < 12:
        raise IOError("truncated TFRecord header in %s at %d" % (path, pos))
      (length,) = struct.unpack("<Q", header[:8])
      offsets.append(pos)
      pos += 12 + length + 4
      f.seek(pos)
  return np.asarray(offsets, dtype=np.int64)


def _sidecar_path(path: str) -> str:
  return path + _IDX_SUFFIX


def build_index(path: str, cache: bool = True) -> np.ndarray:
  """Record byte-offsets for one TFRecord file, with sidecar caching.

  The sidecar stores the indexed file's (size, mtime_ns) for staleness
  detection — size alone would miss a same-size rewrite whose record
  boundaries moved. Remote (fsspec) files are indexed but not
  sidecar-cached — writing next to remote data is often not permitted.
  """
  from tensorflowonspark_tpu.data import fs
  from tensorflowonspark_tpu.utils import paths as _paths
  remote = fs.is_remote(path)
  data_size = fs.file_size(path)
  mtime_ns = 0 if remote else os.stat(_paths.strip_scheme(path)).st_mtime_ns
  side = _sidecar_path(path)
  if cache and not remote and os.path.exists(side):
    try:
      with open(side, "rb") as f:
        magic = f.read(len(_IDX_MAGIC))
        if magic == _IDX_MAGIC:
          (indexed_size, indexed_mtime,
           count) = struct.unpack("<QQQ", f.read(24))
          if indexed_size == data_size and indexed_mtime == mtime_ns:
            offsets = np.frombuffer(f.read(8 * count), dtype="<i8")
            if len(offsets) == count:
              return offsets.astype(np.int64)
        logger.warning("stale/corrupt index sidecar %s; rebuilding", side)
    except (OSError, struct.error) as e:
      logger.warning("unreadable index sidecar %s (%s); rebuilding", side, e)
  offsets = _scan_offsets(path)
  if cache and not remote:
    tmp = side + ".tmp.%d" % os.getpid()
    try:
      with open(tmp, "wb") as f:
        f.write(_IDX_MAGIC)
        f.write(struct.pack("<QQQ", data_size, mtime_ns, len(offsets)))
        f.write(offsets.astype("<i8").tobytes())
      os.replace(tmp, side)   # atomic: concurrent builders race benignly
    except OSError as e:
      logger.warning("cannot write index sidecar %s (%s)", side, e)
  return offsets


# ---------------------------------------------------------------------------
# Random-access dataset
# ---------------------------------------------------------------------------


class IndexedTFRecordDataset(object):
  """A global random-access view over a list of TFRecord files.

  ``record(i)`` decodes like ``readers.read_tfrecord_examples`` (schema
  tuple rows via dfutil, else raw feature dicts), so a sequential pipeline
  can switch to the checkpointable one without touching its model code.
  File handles are opened lazily and kept open per file (shuffled access
  revisits files constantly; per-record reopen would thrash remote FS).
  """

  def __init__(self, paths: Sequence[str], schema=None, cache: bool = True,
               max_open_files: int = 64):
    if not paths:
      raise ValueError("IndexedTFRecordDataset needs at least one file")
    self.paths = list(paths)
    self.schema = schema
    self.max_open_files = max(1, max_open_files)
    self._offsets = [build_index(p, cache=cache) for p in self.paths]
    counts = np.asarray([len(o) for o in self._offsets], dtype=np.int64)
    self._starts = np.concatenate([[0], np.cumsum(counts)])
    import collections
    self._files = collections.OrderedDict()   # LRU of open handles

  def __len__(self) -> int:
    return int(self._starts[-1])

  def fingerprint(self) -> str:
    """Identity of the file layout (basenames + per-file record counts).
    Rides in ``CheckpointableInput`` states so a resume against
    re-sharded/regenerated data of coincidentally equal total length
    fails loudly instead of silently remapping indices. Basenames, not
    full paths: a dataset copied to another root still resumes."""
    import hashlib
    parts = ["%s:%d" % (os.path.basename(p), len(o))
             for p, o in zip(self.paths, self._offsets)]
    return hashlib.md5("|".join(parts).encode()).hexdigest()[:16]

  def _locate(self, index: int):
    if not 0 <= index < len(self):
      raise IndexError("record %d out of range [0, %d)" % (index, len(self)))
    file_i = int(np.searchsorted(self._starts, index, side="right") - 1)
    return file_i, int(index - self._starts[file_i])

  def _file(self, file_i: int):
    f = self._files.get(file_i)
    if f is not None:
      self._files.move_to_end(file_i)
      return f
    from tensorflowonspark_tpu.data import fs
    while len(self._files) >= self.max_open_files:
      # evict least-recently-used so many-file datasets (shuffled access
      # touches every file early) never exhaust the fd/socket limit
      _, old = self._files.popitem(last=False)
      try:
        old.close()
      except OSError:
        pass
    f = fs.open_file(self.paths[file_i], "rb")
    self._files[file_i] = f
    return f

  def raw_record(self, index: int) -> bytes:
    file_i, rec_i = self._locate(index)
    f = self._file(file_i)
    f.seek(int(self._offsets[file_i][rec_i]))
    header = f.read(12)
    if len(header) < 12:
      raise IOError("truncated header for record %d in %s (stale index? "
                    "delete %s)" % (rec_i, self.paths[file_i],
                                    _sidecar_path(self.paths[file_i])))
    (length,) = struct.unpack("<Q", header[:8])
    payload = f.read(length)
    if len(payload) < length:
      raise IOError("truncated record %d in %s" % (rec_i, self.paths[file_i]))
    return payload

  def record(self, index: int):
    from tensorflowonspark_tpu.data import dfutil, example_codec
    raw = self.raw_record(index)
    if self.schema is not None:
      return dfutil.from_example(raw, self.schema)
    return example_codec.decode_example(raw)

  def close(self) -> None:
    for f in self._files.values():
      try:
        f.close()
      except OSError:
        pass
    self._files.clear()


# ---------------------------------------------------------------------------
# Feistel index permutation
# ---------------------------------------------------------------------------


def _mix(x: int, key: int) -> int:
  """splitmix64-style avalanche; the Feistel round function."""
  x = (x + key) & 0xFFFFFFFFFFFFFFFF
  x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
  x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
  return x ^ (x >> 31)


def permute_index(i: int, n: int, key: int, rounds: int = 4) -> int:
  """The position of ``i`` under a seeded bijection of ``[0, n)``.

  A balanced Feistel network over the smallest even-bit-width domain
  covering ``n``, cycle-walking values that land outside ``[0, n)`` back
  through the cipher (expected < 4 walks since the domain is < 4n). O(1)
  memory — a billion-record global shuffle never materializes an array.
  """
  if n <= 1:
    return 0
  half_bits = ((n - 1).bit_length() + 1) // 2
  mask = (1 << half_bits) - 1
  while True:
    left, right = i >> half_bits, i & mask
    for r in range(rounds):
      left, right = right, left ^ (_mix(right, key + r) & mask)
    i = (left << half_bits) | right
    if i < n:
      return i


# ---------------------------------------------------------------------------
# Checkpointable iterator
# ---------------------------------------------------------------------------


class CheckpointableInput(object):
  """Deterministic, sharded, resumable batch iterator.

  The stream is defined purely by (dataset length, seed, shard, batch
  size): position ``p`` of this worker's stream maps to global sample
  position ``p * num_shards + shard_index``, which maps through the
  epoch's Feistel key to a record index. State is therefore just ``p``
  (``get_state()``/``set_state()``/``state`` property), and two iterators
  with equal config and state yield identical batches forever.

  ``num_epochs=None`` streams indefinitely (epoch = position // len).
  With ``shuffle=False`` the permutation is the identity (useful for eval
  sweeps that still want exact resume).
  """

  def __init__(self, dataset, batch_size: int, shard_index: int = 0,
               num_shards: int = 1, seed: int = 0, shuffle: bool = True,
               num_epochs: Optional[int] = None, drop_remainder: bool = True,
               collate=None):
    if num_shards < 1 or not 0 <= shard_index < num_shards:
      raise ValueError("bad shard spec %d/%d" % (shard_index, num_shards))
    if batch_size < 1:
      raise ValueError("batch_size must be >= 1")
    self.dataset = dataset
    self.batch_size = batch_size
    self.shard_index = shard_index
    self.num_shards = num_shards
    self.seed = seed
    self.shuffle = shuffle
    self.num_epochs = num_epochs
    self.drop_remainder = drop_remainder
    self._collate = collate or self._default_collate
    self._pos = 0

  @staticmethod
  def _default_collate(batch):
    if isinstance(batch[0], (tuple, list)):
      return tuple(np.asarray([row[i] for row in batch])
                   for i in range(len(batch[0])))
    return np.asarray(batch)

  # -- state ---------------------------------------------------------------

  @property
  def state(self) -> dict:
    return self.get_state()

  def get_state(self) -> dict:
    """A tiny JSON-safe dict. ``config`` rides along so a restore into a
    differently-configured iterator fails loudly instead of silently
    yielding a different stream."""
    cfg = {"len": len(self.dataset), "seed": self.seed,
           "shard_index": self.shard_index,
           "num_shards": self.num_shards,
           "batch_size": self.batch_size,
           "shuffle": self.shuffle}
    fp = getattr(self.dataset, "fingerprint", None)
    if fp is not None:
      cfg["data_fingerprint"] = fp()
    return {"position": self._pos, "config": cfg}

  def set_state(self, state: dict) -> None:
    cfg = state.get("config")
    if cfg is not None and cfg != self.get_state()["config"]:
      raise ValueError(
          "iterator state was saved under a different input config: "
          "%r vs %r — resume with identical data/shard/batch settings"
          % (cfg, self.get_state()["config"]))
    self._pos = int(state["position"])

  # -- iteration -----------------------------------------------------------

  def _epoch_len(self) -> int:
    """Samples per epoch for THIS worker (global stream is sharded
    round-robin in sample space)."""
    n = len(self.dataset)
    base, extra = divmod(n, self.num_shards)
    return base + (1 if self.shard_index < extra else 0)

  def _record_index(self, worker_pos: int) -> int:
    n = len(self.dataset)
    per_epoch = self._epoch_len()
    epoch, within = divmod(worker_pos, per_epoch)
    global_pos = within * self.num_shards + self.shard_index
    if not self.shuffle:
      return global_pos
    return permute_index(global_pos, n, _mix(self.seed, epoch))

  def __iter__(self) -> Iterator:
    per_epoch = self._epoch_len()
    if per_epoch == 0:
      # this worker's sample-space slice is empty (more shards than
      # records). Finite mode: an empty stream. Streaming mode: raise,
      # matching readers.read_tfrecord_examples(repeat=True) — an
      # endless empty iterator would hang a synchronous training loop.
      if self.num_epochs is None:
        raise ValueError(
            "streaming iteration over an empty shard (%d records, shard "
            "%d/%d) would never yield; size shards to workers instead"
            % (len(self.dataset), self.shard_index, self.num_shards))
      return
    while True:
      if self.num_epochs is not None:
        end = self.num_epochs * per_epoch
        if self._pos >= end:
          return
        room = end - self._pos
        if room < self.batch_size and self.drop_remainder:
          self._pos = end
          return
        take = min(self.batch_size, room)
      else:
        take = self.batch_size
      rows = [self.dataset.record(self._record_index(self._pos + j))
              for j in range(take)]
      # state advances only after a batch is fully assembled: a crash
      # mid-batch resumes AT this batch, never past it
      self._pos += take
      yield self._collate(rows)


def checkpointable_input(pattern_or_paths, batch_size: int, schema=None,
                         shard_index: int = 0, num_shards: int = 1,
                         seed: int = 0, shuffle: bool = True,
                         num_epochs: Optional[int] = None,
                         drop_remainder: bool = True,
                         collate=None) -> CheckpointableInput:
  """Glob/list -> ``CheckpointableInput`` in one call.

  NOTE the sharding difference from ``readers.shard_files``: files are NOT
  pre-sharded per worker — every worker indexes the full file list and
  takes its slice in sample space, so shards stay balanced even when file
  sizes aren't, and the worker count can change between runs as long as
  resume states aren't carried across a reshard (set_state checks).
  """
  from tensorflowonspark_tpu.data import fs
  if isinstance(pattern_or_paths, str):
    paths = sorted(fs.glob_files(pattern_or_paths))
  else:
    paths = sorted(pattern_or_paths)
  if not paths:
    raise FileNotFoundError("no input files match %r" % (pattern_or_paths,))
  ds = IndexedTFRecordDataset(paths, schema=schema)
  return CheckpointableInput(
      ds, batch_size, shard_index=shard_index, num_shards=num_shards,
      seed=seed, shuffle=shuffle, num_epochs=num_epochs,
      drop_remainder=drop_remainder, collate=collate)


__all__ = ["build_index", "IndexedTFRecordDataset", "permute_index",
           "CheckpointableInput", "checkpointable_input"]
