"""Autotuned declarative input pipeline over the columnar chunk plane.

The feed plane so far is a dumb conveyor (the reference's
``InputMode.SPARK`` shape): ``datafeed._FetchPipeline`` is ONE
fixed-depth fetch thread, and every map/shuffle/batch decision lives in
user code between ``next_batch_arrays`` and the jitted step. The tf.data
paper (PAPERS.md, arXiv 2101.12127) shows the winning design — a lazy,
declarative graph of composable transforms whose per-stage parallelism
and buffer depths are *autotuned* online — and this module is that
design at :class:`~tensorflowonspark_tpu.control.chunkcodec.ColumnChunk`
granularity:

- :class:`Dataset` is the lazy graph: ``from_feed(feed)`` /
  ``from_chunks(...)`` sources (plus ``Dataset.interleave([...])`` for
  parallel reads across hubs/files) composed with ``.map(fn)``,
  ``.filter(pred)``, ``.shuffle(buffer_rows)``, ``.batch(B)`` /
  ``.slab(B, K)`` and ``.prefetch(depth)``. Nothing runs until
  ``.batches()`` / ``.start()``.
- Transforms have a COLUMNAR fast path (``columnar=True``: the fn sees
  whole column arrays, vectorized over the chunk, no per-row Python
  loop) and a row fallback (the fn sees one row at a time; results are
  re-columnarized when homogeneous so the downstream stages stay on the
  fast path).
- :class:`GraphExecutor` is ``_FetchPipeline`` grown into a multi-stage
  executor: per-stage bounded hand-off buffers (:class:`_Buffer`, whose
  ``pipe_get``/``pipe_put`` verbs are in the analyzer's TOS001
  bounded-wait set — every wait is timeout-bounded) and worker pools,
  with an online :class:`_Autotuner` that reallocates stage parallelism
  and buffer depths from the live per-stage gauges (the same
  dominant-stage attribution the obs plane's ``feed_stall`` detector
  uses as its error signal — docs/OBSERVABILITY.md).
- ``deterministic=True`` (the default) pins element order end to end —
  per-stage sequence-ordered emit, round-robin interleave — so
  ``from_feed(feed).slab(B, K)`` yields the exact batches
  ``data.readers.slab_batches(feed, B, K)`` yields and the fused train
  loop's bit-identical-trajectory contract composes with the graph.
  ``deterministic=False`` is the throughput mode: map/filter outputs
  emit as they finish and interleave pulls whichever source is ready
  (markers still act as order barriers, so end-of-feed /
  ``EndPartition`` semantics survive).

Marker semantics are IDENTICAL to ``feed_batches``/``slab_batches``:
end-of-feed flushes a partial final batch and ends the stream;
``EndPartition`` is skipped in train mode and ends the
batch/slab-stretch early in inference mode (short stretches split into
the same per-step batches ``slab_batches`` would yield — what makes the
fused trajectory bit-identical through the graph).

Env knobs (registry: TOS008; see docs/API.md §datapipe):

==========================  ==================================================
``TOS_DATA_AUTOTUNE``       online autotuner on/off (default on; the gauge
                            mirror keeps running either way)
``TOS_DATA_AUTOTUNE_INTERVAL``  seconds between autotune passes (default 0.5)
``TOS_DATA_MAX_WORKERS``    per-stage worker cap (default 4)
``TOS_DATA_BUFFER_CAP``     per-stage hand-off buffer depth cap (default 32)
==========================  ==================================================
"""

import collections
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from tensorflowonspark_tpu.control import chunkcodec
from tensorflowonspark_tpu.control.marker import Marker
from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans

logger = logging.getLogger(__name__)

#: online autotuner master switch (default on; ``0`` keeps the executor
#: at its declared worker/depth plan) — env registry: TOS008
ENV_DATA_AUTOTUNE = "TOS_DATA_AUTOTUNE"
#: seconds between autotune passes (also the stage-gauge mirror cadence)
ENV_DATA_AUTOTUNE_INTERVAL = "TOS_DATA_AUTOTUNE_INTERVAL"
#: per-stage worker-pool cap the autotuner may grow to (TOS008)
ENV_DATA_MAX_WORKERS = "TOS_DATA_MAX_WORKERS"
#: per-stage hand-off buffer depth cap the autotuner may grow to (TOS008)
ENV_DATA_BUFFER_CAP = "TOS_DATA_BUFFER_CAP"
#: feeder-side transform pushdown master switch (default on; ``0`` keeps
#: every stage consumer-side — :meth:`Dataset.split_pushdown` then always
#: returns the whole graph as the consumer segment) — env registry: TOS008
ENV_FEED_PUSHDOWN = "TOS_FEED_PUSHDOWN"

_DEFAULT_INTERVAL = 0.5
_DEFAULT_MAX_WORKERS = 4
_DEFAULT_BUFFER_CAP = 32
#: initial hand-off depth per stage (the `_FetchPipeline` default)
_DEFAULT_DEPTH = 2

#: bound on every blocking wait inside the executor (TOS001: a wedged
#: consumer or producer must never pin a worker past its stop check)
_POLL = 0.25

#: a stage must run at/above this busy fraction (per worker) before the
#: autotuner calls it dominant and spends a move on it
_HOT_UTIL = 0.5
#: a stage below this busy fraction per worker donates a worker back
_COLD_UTIL = 0.05

_EMPTY = object()   # pipe_get timeout sentinel (None is a real marker)


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


def _env_int(name: str, default: int) -> int:
  try:
    return int(os.environ.get(name, default))
  except ValueError:
    return default


# -- chunk helpers ------------------------------------------------------------


def _rows_to_chunk(rows: List) -> Optional[chunkcodec.ColumnChunk]:
  """Best-effort columnarization of a row list (no codec round-trip).

  The in-process analog of ``chunkcodec.encode``'s eligibility rules:
  homogeneous ndarray columns stack, exact python bool/int/float scalar
  columns pack (dtype kind must round-trip the python type — the codec's
  int-beyond-int64 rule). Returns None when the rows are heterogeneous —
  the caller keeps the row representation and downstream stages use
  their row fallbacks.
  """
  import numpy as np
  if not rows:
    return None
  first = rows[0]
  tuples = isinstance(first, tuple)
  if tuples:
    width = len(first)
    if width == 0 or not all(isinstance(r, tuple) and len(r) == width
                             for r in rows):
      return None
    columns = [[r[j] for r in rows] for j in range(width)]
  else:
    if isinstance(first, (Marker,)) or first is None:
      return None
    columns = [rows]
  cols, scalar = [], []
  for values in columns:
    v0 = values[0]
    if isinstance(v0, np.ndarray):
      dtype, shape = v0.dtype, v0.shape
      if dtype == object or not all(
          isinstance(v, np.ndarray) and v.dtype == dtype and v.shape == shape
          for v in values):
        return None
      cols.append(np.stack(values))
      scalar.append(0)
      continue
    kind = type(v0)
    if kind not in (bool, int, float) or \
        not all(type(v) is kind for v in values):
      return None
    try:
      arr = np.asarray(values)
    except OverflowError:
      return None
    if arr.dtype.kind != {bool: "b", int: "i", float: "f"}[kind]:
      return None
    cols.append(arr)
    scalar.append(1)
  return chunkcodec.ColumnChunk(cols, scalar, tuples, len(rows))


def _chunk_from_cols(cols: Sequence, like: chunkcodec.ColumnChunk
                     ) -> chunkcodec.ColumnChunk:
  """Wrap transform output columns as a ColumnChunk (schema may differ
  from ``like``; scalar flags carry over positionally where they can)."""
  import numpy as np
  cols = [np.asarray(c) for c in cols]
  n = len(cols[0])
  if any(len(c) != n for c in cols):
    raise ValueError("columnar transform returned columns of unequal "
                     "length: %r" % ([len(c) for c in cols],))
  if len(cols) == len(like.cols):
    scalar = list(like.scalar)
  else:
    scalar = [1 if c.ndim == 1 else 0 for c in cols]
  tuples = like.tuples or len(cols) > 1
  return chunkcodec.ColumnChunk(cols, scalar, tuples, n)


def _split_inline_markers(item) -> List:
  """Expand a legacy row-list payload carrying INLINE markers (raw
  ``put_many`` streams — chunk-boundary envelopes ship markers alone)
  into marker-free segments with the markers as standalone items, in
  stream order."""
  kind, payload = item
  if kind != "data" or not isinstance(payload, list) or not any(
      r is None or isinstance(r, Marker) for r in payload):
    return [item]
  out: List = []
  seg: List = []
  for r in payload:
    if r is None or isinstance(r, Marker):
      if seg:
        chunk = _rows_to_chunk(seg)
        out.append(("data", chunk if chunk is not None else seg))
        seg = []
      out.append(("marker", r))
      if r is None:
        return out      # end-of-feed: nothing rides behind it
    else:
      seg.append(r)
  if seg:
    chunk = _rows_to_chunk(seg)
    out.append(("data", chunk if chunk is not None else seg))
  return out


def _normalize_source_item(obj):
  """Coerce one ``from_chunks`` element to the wire union
  (``("data", ColumnChunk|rows)`` / ``("marker", m)``)."""
  if obj is None or isinstance(obj, Marker):
    return ("marker", obj)
  if isinstance(obj, chunkcodec.ColumnChunk):
    return ("data", obj)
  if isinstance(obj, tuple) and len(obj) == 2 and obj[0] in ("data", "marker"):
    return obj
  if isinstance(obj, list):
    chunk = _rows_to_chunk(obj)
    return ("data", chunk if chunk is not None else obj)
  raise TypeError("from_chunks elements must be ColumnChunk, row list, "
                  "Marker or None (end-of-feed); got %r" % (type(obj),))


# -- bounded hand-off buffer --------------------------------------------------


class _Buffer(object):
  """Depth-bounded stage hand-off with a RESIZABLE capacity.

  ``queue.Queue``'s maxsize is fixed at construction; the autotuner
  needs to deepen a starved stage's buffer online, so this is a small
  condition-variable deque with a mutable ``capacity``. ``pipe_put`` /
  ``pipe_get`` are in the analyzer's TOS001 bounded-wait verb set:
  every call sites an explicit ``timeout``.
  """

  def __init__(self, capacity: int):
    self._cond = threading.Condition()
    self._items: collections.deque = collections.deque()
    self._capacity = max(1, int(capacity))

  @property
  def capacity(self) -> int:
    return self._capacity

  def set_capacity(self, n: int) -> None:
    with self._cond:
      self._capacity = max(1, int(n))
      self._cond.notify_all()

  def __len__(self) -> int:
    with self._cond:
      return len(self._items)

  def pipe_put(self, item, timeout: float) -> bool:
    """Append ``item`` within ``timeout`` seconds; False on timeout."""
    deadline = time.monotonic() + timeout
    with self._cond:
      while len(self._items) >= self._capacity:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          return False
        self._cond.wait(timeout=min(remaining, _POLL))
      self._items.append(item)
      self._cond.notify_all()
      return True

  def pipe_get(self, timeout: float):
    """Pop the oldest item within ``timeout`` seconds; ``_EMPTY`` on
    timeout (None is a real payload: the end-of-feed marker)."""
    deadline = time.monotonic() + timeout
    with self._cond:
      while not self._items:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          return _EMPTY
        self._cond.wait(timeout=min(remaining, _POLL))
      item = self._items.popleft()
      self._cond.notify_all()
      return item


class _OrderedEmitter(object):
  """Order-restoring boundary between a stage's worker pool and the next
  stage's buffer.

  Workers finish out of order (that is the point of the pool); the
  emitter re-serializes. ``deterministic=True``: every item is released
  in input-sequence order, so the graph's element order is a pure
  function of the source order. ``deterministic=False``: data items are
  released the moment their worker finishes (throughput mode), but
  MARKERS are order barriers both ways: a marker waits for every
  earlier item, and data behind an in-flight marker (announced by the
  upstream emitter via :meth:`expect_marker` before the marker enters
  the buffer, so there is no pull-race window) waits for the marker —
  end-of-feed and ``EndPartition`` keep their stream positions either
  way. The holding map is bounded by the stage's worker count (workers
  pull FIFO, so at most ``workers`` sequences are in flight).
  """

  def __init__(self, out: _Buffer, deterministic: bool):
    self._out = out
    self._det = deterministic
    self._lock = threading.Lock()
    self._next = 0          # next input seq to release
    self._held: Dict[int, List] = {}
    self._out_seq = 0
    #: the NEXT stage's emitter (None for the consumer-facing tail);
    #: marker seqs are announced to it at push time so its throughput-
    #: mode fast path can't let later data overtake an in-flight marker
    self.downstream: Optional["_OrderedEmitter"] = None
    self._expected_markers: set = set()   # announced, not yet released

  def expect_marker(self, seq: int) -> None:
    """Upstream announces: input ``seq`` is a marker (called BEFORE the
    marker enters this stage's input buffer, so the barrier is in place
    by the time any later data item can possibly reach :meth:`emit`)."""
    with self._lock:
      self._expected_markers.add(seq)

  def _push(self, outputs, stop: threading.Event, stats: Dict) -> bool:
    for item in outputs:
      seq = self._out_seq
      self._out_seq += 1
      if self.downstream is not None and self._is_marker(item):
        self.downstream.expect_marker(seq)
      t0 = time.perf_counter()
      while True:
        if self._out.pipe_put((seq, item), timeout=_POLL):
          break
        if stop.is_set():
          return False
      stats["out_wait_s"] += time.perf_counter() - t0
    return True

  @staticmethod
  def _is_marker(item) -> bool:
    return item[0] in ("marker", "end")

  def emit(self, seq: int, outputs: List, stop: threading.Event,
           stats: Dict) -> bool:
    """Hand one input sequence's outputs to the next stage. Returns
    False when the executor stopped mid-push."""
    with self._lock:
      if not self._det and not any(self._is_marker(i) for i in outputs) \
          and (not self._expected_markers
               or seq < min(self._expected_markers)):
        # throughput mode: data flushes now (no in-flight marker below
        # it — markers are order barriers); the seq is marked done so
        # held markers behind it can advance
        if seq == self._next or seq in self._held:
          pass    # in-order anyway (or duplicate): fall through to held
        else:
          if not self._push(outputs, stop, stats):
            return False
          self._held[seq] = []
          return self._advance(stop, stats)
      self._held[seq] = outputs
      return self._advance(stop, stats)

  def _advance(self, stop: threading.Event, stats: Dict) -> bool:
    while self._next in self._held:
      outputs = self._held.pop(self._next)
      self._expected_markers.discard(self._next)
      self._next += 1
      if outputs and not self._push(outputs, stop, stats):
        return False
    return True


# -- stage transform bodies ---------------------------------------------------


def _make_map(fn: Callable, columnar: bool) -> Callable:
  """A map stage body: item -> [item]. Markers pass through untouched."""

  def _apply(item):
    kind, payload = item
    if kind != "data":
      return [item]
    if isinstance(payload, chunkcodec.ColumnChunk):
      if columnar:
        out = fn(*payload.cols)
        cols = list(out) if isinstance(out, (tuple, list)) else [out]
        return [("data", _chunk_from_cols(cols, payload))]
      rows = [fn(r) for r in payload.rows()]
    else:
      if columnar:
        chunk = _rows_to_chunk(payload)
        if chunk is None:
          raise TypeError(
              "columnar map received a heterogeneous row chunk it cannot "
              "columnarize; use map(fn, columnar=False) for this stream")
        out = fn(*chunk.cols)
        cols = list(out) if isinstance(out, (tuple, list)) else [out]
        return [("data", _chunk_from_cols(cols, chunk))]
      rows = [fn(r) for r in payload]
    chunk = _rows_to_chunk(rows)
    return [("data", chunk if chunk is not None else rows)]

  return _apply


def _make_filter(pred: Callable, columnar: bool) -> Callable:
  """A filter stage body: item -> [item] (or [] when nothing survives)."""
  import numpy as np

  def _apply(item):
    kind, payload = item
    if kind != "data":
      return [item]
    if isinstance(payload, chunkcodec.ColumnChunk):
      if columnar:
        mask = np.asarray(pred(*payload.cols), dtype=bool).reshape(-1)
        if mask.shape[0] != payload.n:
          raise ValueError("columnar filter mask has %d entries for a "
                           "%d-row chunk" % (mask.shape[0], payload.n))
      else:
        mask = np.fromiter((bool(pred(r)) for r in payload.rows()),
                           dtype=bool, count=payload.n)
      if mask.all():
        return [item]
      if not mask.any():
        return []
      cols = [c[mask] for c in payload.cols]
      return [("data", chunkcodec.ColumnChunk(
          cols, list(payload.scalar), payload.tuples, int(mask.sum())))]
    rows = payload
    if columnar:
      chunk = _rows_to_chunk(rows)
      if chunk is None:
        raise TypeError(
            "columnar filter received a heterogeneous row chunk it cannot "
            "columnarize; use filter(pred, columnar=False)")
      return _apply(("data", chunk))
    kept = [r for r in rows if pred(r)]
    if not kept:
      return []
    chunk = _rows_to_chunk(kept)
    return [("data", chunk if chunk is not None else kept)]

  return _apply


class FeederSegment(object):
  """The pushable prefix of a :class:`Dataset` graph, run FEEDER-side.

  Holds the leading stateless ``map``/``filter`` ops split off by
  :meth:`Dataset.split_pushdown`. The segment travels to feeder tasks via
  cluster_meta (cloudpickled with the task closure, like the user fns)
  and executes inside the feeder BEFORE ``node.put_rows_chunk`` encodes —
  a filtered row never touches the codec, a projecting map shrinks
  columns before the wire.

  Pushdown moves COMPUTATION, never ORDER: the ops are applied to each
  chunk in stream position by the same stage bodies the consumer-side
  executor would run (``_make_map``/``_make_filter``), so
  ``deterministic=True`` and the fused-loop bit-identical-trajectory
  contract hold unchanged. Markers never enter a segment — they ride
  alone as chunk-boundary envelopes outside ``put_rows_chunk``.
  """

  __slots__ = ("ops",)

  def __init__(self, ops: List):
    self.ops = list(ops)

  def compile(self) -> Callable:
    """Build the feeder-side runner: ``rows -> ColumnChunk | rows | None``
    (None when the segment filters the whole chunk away). Built once per
    feeder task; the bodies are exactly the consumer-side stage bodies."""
    bodies = [_make_map(fn, columnar) if kind == "map"
              else _make_filter(fn, columnar)
              for kind, fn, columnar in self.ops]

    def _run(rows):
      chunk = _rows_to_chunk(rows)
      items = [("data", chunk if chunk is not None else rows)]
      for body in bodies:
        out = []
        for item in items:
          out.extend(body(item))
        items = out
        if not items:
          return None
      # map/filter bodies are 1 -> <=1, so one item survives at most
      return items[0][1]

    return _run

  def __repr__(self):
    return "FeederSegment(%s)" % ",".join(op[0] for op in self.ops)


class _ShuffleState(object):
  """Streaming row-granular shuffle at COLUMN granularity.

  Holds up to ``buffer_rows`` rows; once the buffer overflows, the
  overflow count is drawn uniformly (vectorized gather — one
  ``np.take`` per column, no per-row loop) and emitted as a fresh
  chunk. Markers flush the whole buffer shuffled first, so rows never
  cross an ``EndPartition`` / end-of-feed boundary. Deterministic per
  ``seed`` + arrival order. Heterogeneous row chunks (and schema
  changes) flush and fall back to row-list shuffling. Stateful —
  single-worker by construction (the planner pins it).
  """

  def __init__(self, buffer_rows: int, seed: int = 0):
    import numpy as np
    self._buffer_rows = max(1, int(buffer_rows))
    self._rng = np.random.RandomState(seed)
    self._cols = None         # list of per-column array-piece lists
    self._sig = None
    self._scalar = None
    self._tuples = False
    self._n = 0
    self._rows: List = []     # heterogeneous fallback buffer

  def _sig_of(self, chunk):
    return (len(chunk.cols),
            tuple((a.dtype.str, a.shape[1:]) for a in chunk.cols))

  def _flush_all(self) -> List:
    import numpy as np
    out = []
    if self._n:
      cols = [np.concatenate(p) for p in self._cols]
      perm = self._rng.permutation(self._n)
      cols = [c[perm] for c in cols]
      out.append(("data", chunkcodec.ColumnChunk(
          cols, list(self._scalar), self._tuples, self._n)))
      self._cols, self._sig, self._n = None, None, 0
    if self._rows:
      rows = list(self._rows)
      self._rng.shuffle(rows)
      out.append(("data", rows))
      self._rows = []
    return out

  def _emit_overflow(self) -> List:
    import numpy as np
    out = []
    while self._n > self._buffer_rows:
      take = self._n - self._buffer_rows
      cols = [np.concatenate(p) for p in self._cols]
      idx = self._rng.permutation(self._n)
      sent, kept = idx[:take], idx[take:]
      out.append(("data", chunkcodec.ColumnChunk(
          [c[sent] for c in cols], list(self._scalar), self._tuples, take)))
      self._cols = [[c[kept]] for c in cols]
      self._n = len(kept)
    return out

  def feed(self, item) -> List:
    kind, payload = item
    if kind != "data":
      return self._flush_all() + [item]
    if not isinstance(payload, chunkcodec.ColumnChunk):
      chunk = _rows_to_chunk(payload)
      if chunk is None:
        # heterogeneous rows: flush the columnar buffer, buffer rows
        out = self._flush_all() if self._n else []
        self._rows.extend(payload)
        if len(self._rows) > self._buffer_rows:
          rows = list(self._rows)
          self._rng.shuffle(rows)
          take = len(rows) - self._buffer_rows
          out.append(("data", rows[:take]))
          self._rows = rows[take:]
        return out
      payload = chunk
    out = []
    sig = self._sig_of(payload)
    if self._rows or (self._sig is not None and sig != self._sig):
      out.extend(self._flush_all())
    if self._sig is None or self._n == 0:
      self._sig = sig
      self._scalar = list(payload.scalar)
      self._tuples = payload.tuples
      self._cols = [[] for _ in payload.cols]
      self._n = 0
    for pieces, col in zip(self._cols, payload.cols):
      pieces.append(col)
    self._n += payload.n
    out.extend(self._emit_overflow())
    return out


class _AssembleState(object):
  """The terminal batch/slab assembly stage — ``_assemble_columns`` +
  ``slab_batches`` semantics reproduced over the in-executor stream.

  Plans rows across chunk boundaries and commits one output per
  ``batch_size`` (or ``batch_size*unroll`` for slabs): each output
  column is ONE ``np.concatenate`` over chunk slices (the hand-off
  copy, exactly the DataFeed fast path). Markers keep their row-path
  semantics: end-of-feed flushes the partial tail and ends the stream;
  ``EndPartition`` is skipped in train mode and ends the stretch in
  inference mode. A short SLAB stretch splits into the same per-step
  batches ``slab_batches`` yields (full ones first, short remainder
  last) — the bit-identical-trajectory contract. Stateful —
  single-worker by construction.
  """

  def __init__(self, batch_size: int, unroll: int = 1, dtype=None,
               columns: Optional[List[str]] = None, train_mode: bool = True):
    self.batch_size = int(batch_size)
    self.unroll = max(1, int(unroll))
    self.dtype = dtype
    self.columns = columns
    self.train_mode = train_mode
    self._plan: List = []      # (ColumnChunk, start, stop) in plan order
    self._rows: List = []      # row-mode fallback for the current stretch
    self._sig = None
    self._have = 0

  @property
  def _want(self) -> int:
    return self.batch_size * self.unroll

  def _demote_to_rows(self) -> None:
    rows = []
    for cc, a, b in self._plan:
      rows.extend(cc.rows(a)[:b - a])
    self._plan, self._sig = [], None
    self._rows = rows + self._rows

  def _emit_columns(self, arrays: List, n: int):
    """Shape one flushed stretch into the output payload(s)."""
    out = []
    if self.unroll > 1 and n == self._want:
      from tensorflowonspark_tpu.data.readers import Slab
      stacked = [a.reshape((self.unroll, self.batch_size) + a.shape[1:])
                 for a in arrays]
      if self.columns is not None:
        out.append(("batch", Slab(dict(zip(self.columns, stacked)))))
      elif len(stacked) == 1:
        out.append(("batch", Slab(stacked[0])))
      else:
        out.append(("batch", Slab(tuple(stacked))))
      return out
    # plain batches — and the short-slab tail split (full per-step
    # batches first, short remainder last: slab_batches order)
    for i in range(0, n, self.batch_size):
      part = [a[i:i + self.batch_size] for a in arrays]
      if self.columns is not None:
        out.append(("batch", dict(zip(self.columns, part))))
      elif len(part) == 1:
        out.append(("batch", part[0]))
      else:
        out.append(("batch", tuple(part)))
    return out

  def _flush(self) -> List:
    import numpy as np
    if self._rows:
      # row-mode stretch: stack per column (same values the columnar
      # concatenate yields for homogeneous rows)
      rows = self._rows
      self._rows = []
      if isinstance(rows[0], tuple):
        ncols = len(rows[0])
        arrays = [np.asarray([r[j] for r in rows]) for j in range(ncols)]
      else:
        arrays = [np.asarray(rows)]
    elif self._plan:
      ncols = len(self._plan[0][0].cols)
      if self.columns is not None:
        ncols = min(ncols, len(self.columns))
      arrays = []
      for j in range(ncols):
        pieces = [cc.cols[j][a:b] for cc, a, b in self._plan]
        arrays.append(np.concatenate(pieces)
                      if len(pieces) > 1 else np.asarray(pieces[0]))
      self._plan, self._sig = [], None
    else:
      return []
    if self.dtype is not None:
      dt = np.dtype(self.dtype)
      arrays = [a if a.dtype == dt else a.astype(dt) for a in arrays]
    n = len(arrays[0])
    self._have = 0
    return self._emit_columns(arrays, n)

  def feed(self, item) -> List:
    kind, payload = item
    if kind == "marker":
      if payload is None:                  # end-of-feed
        return self._flush() + [("end", None)]
      if self.train_mode:
        return []                          # EndPartition skipped in train
      return self._flush()                 # inference: stretch ends here
    # data
    if isinstance(payload, chunkcodec.ColumnChunk):
      sig = (len(payload.cols),
             tuple((a.dtype.str, a.shape[1:]) for a in payload.cols))
      if self._rows or (self._sig is not None and sig != self._sig):
        self._demote_to_rows()
        self._rows.extend(payload.rows())
        self._have += payload.n
      else:
        self._sig = sig
        self._plan.append((payload, 0, payload.n))
        self._have += payload.n
    else:
      if self._plan:
        self._demote_to_rows()
      self._rows.extend(payload)
      self._have += len(payload)
    out = []
    while self._have >= self._want:
      out.extend(self._take_exact(self._want))
    return out

  def _take_exact(self, want: int) -> List:
    """Split off exactly ``want`` planned rows and flush them."""
    if self._rows:
      head, self._rows = self._rows[:want], self._rows[want:]
      rest_have = self._have - want
      saved_rows, self._rows = self._rows, head
      self._have = want
      out = self._flush()
      self._rows = saved_rows
      self._have = rest_have
      return out
    taken, remaining = [], []
    left = want
    for cc, a, b in self._plan:
      if left <= 0:
        remaining.append((cc, a, b))
        continue
      take = min(left, b - a)
      taken.append((cc, a, a + take))
      left -= take
      if a + take < b:
        remaining.append((cc, a + take, b))
    saved_plan, saved_sig = remaining, self._sig
    rest_have = self._have - want
    self._plan, self._have = taken, want
    out = self._flush()
    self._plan, self._sig = saved_plan, saved_sig
    self._have = rest_have
    return out


# -- the executor -------------------------------------------------------------


class _StageRuntime(object):
  """One executor stage: a worker pool draining an input buffer through
  the transform body into an order-restoring emitter."""

  def __init__(self, name: str, body, parallelizable: bool,
               inbuf: Optional[_Buffer], emitter: _OrderedEmitter,
               stop: threading.Event):
    self.name = name
    self.body = body                      # item -> [item]
    self.parallelizable = parallelizable
    self.inbuf = inbuf
    self.emitter = emitter
    self._stop = stop
    self.target = 1
    self.active = 0          # live workers (a retiring worker decrements)
    self._spawned = 0
    self.threads: List[threading.Thread] = []
    self._lock = threading.Lock()
    # monotonic counters only (snapshot-subtract safe); worker threads
    # read-modify-write these, so readers must go through snapshot_stats
    self.stats = {"busy_s": 0.0, "items": 0, "in_wait_s": 0.0,
                  "out_wait_s": 0.0}

  @property
  def workers(self) -> int:
    return self.target

  def should_retire(self) -> bool:
    """Called by a worker each loop: True exactly once per shrink (the
    caller retires; identity-by-index breaks after shrink+grow cycles,
    a live-count handshake does not)."""
    with self._lock:
      if self.active > self.target:
        self.active -= 1
        return True
      return False

  def spawn(self, executor) -> None:
    with self._lock:
      if self.active >= self.target:
        return
      self.active += 1
      idx = self._spawned
      self._spawned += 1
      # retired workers stay in the list until the next spawn: prune
      # here so grow/shrink oscillation can't accumulate dead Threads
      self.threads = [x for x in self.threads if x.is_alive()]
      t = threading.Thread(target=executor._stage_worker, args=(self, idx),
                           daemon=True,
                           name="tos-pipe-%s-%d" % (self.name, idx))
      self.threads.append(t)
    t.start()

  def grow(self, executor) -> None:
    with self._lock:
      self.target += 1
    self.spawn(executor)

  def shrink(self) -> None:
    with self._lock:
      if self.target > 1:
        self.target -= 1


class GraphExecutor(object):
  """``_FetchPipeline`` grown into a multi-stage pipeline executor.

  Stages hand off through bounded :class:`_Buffer`\\ s; each transform
  stage owns a worker pool whose size (and whose buffer depth) the
  :class:`_Autotuner` reallocates online from the live per-stage
  gauges. Every blocking wait is timeout-bounded (TOS001); a worker
  error is forwarded and re-raised in the consumer; the source thread
  retires itself at end-of-feed. ``stats`` is a live dict mutated by
  the workers — read it through ``stats_snapshot()`` (the PR 4
  snapshot-subtract rule), never by zeroing or raw copies.
  """

  def __init__(self, plan: "Dataset", deterministic: bool = True,
               autotune: Optional[bool] = None):
    self._plan = plan
    self._det = bool(deterministic)
    if autotune is None:
      autotune = os.environ.get(ENV_DATA_AUTOTUNE, "1") not in ("0",)
    self._autotune = bool(autotune)
    self._max_workers = max(1, _env_int(ENV_DATA_MAX_WORKERS,
                                        _DEFAULT_MAX_WORKERS))
    self._buffer_cap = max(1, _env_int(ENV_DATA_BUFFER_CAP,
                                       _DEFAULT_BUFFER_CAP))
    self._stop_evt = threading.Event()
    self._error: Optional[BaseException] = None
    self._stages: List[_StageRuntime] = []
    self._buffers: List[_Buffer] = []
    self._source_threads: List[threading.Thread] = []
    self._tuner: Optional["_Autotuner"] = None
    self.autotune_events: collections.deque = collections.deque(maxlen=256)
    #: live executor-level stats; ``stages`` nests the per-stage dicts
    #: (obs.metrics.snapshot_stats recurses into them)
    self.stats: Dict[str, Any] = {"batches": 0, "rows": 0,
                                  "autotune_moves": 0, "stages": {}}
    # obs seam (docs/OBSERVABILITY.md): cached once, None when off
    self._rec = obs_spans.active()
    reg = obs_metrics.active()
    self._obs_m = None if reg is None else {
        "batches": reg.counter("feed.batches"),
        "rows": reg.counter("feed.rows"),
        "moves": reg.counter("feed.autotune_moves"),
        "reg": reg,
    }
    self._build()

  # -- graph construction ----------------------------------------------------

  def _build(self) -> None:
    ops = self._plan._ops
    depth_after: Dict[int, int] = self._plan._depths
    default_depth = _DEFAULT_DEPTH
    # source -> buffer -> [stage -> buffer]... -> consumer buffer
    self._buffers.append(_Buffer(depth_after.get(-1, default_depth)))
    idx = 0
    for op in ops:
      kind = op[0]
      if kind == "map":
        body, par = _make_map(op[1], op[2]), True
        name = "map%d" % idx
      elif kind == "filter":
        body, par = _make_filter(op[1], op[2]), True
        name = "filter%d" % idx
      elif kind == "shuffle":
        state = _ShuffleState(op[1], op[2])
        body, par = state.feed, False
        name = "shuffle%d" % idx
      elif kind in ("batch", "slab"):
        state = _AssembleState(
            batch_size=op[1], unroll=op[2], dtype=op[3],
            columns=self._plan._columns, train_mode=self._plan._train_mode)
        body, par = state.feed, False
        name = "assemble"
      else:
        raise ValueError("unknown op %r" % (kind,))
      out = _Buffer(depth_after.get(idx, default_depth))
      emitter = _OrderedEmitter(out, self._det)
      stage = _StageRuntime(name, body, par, self._buffers[-1], emitter,
                            self._stop_evt)
      self._stages.append(stage)
      self._buffers.append(out)
      self.stats["stages"][name] = stage.stats
      idx += 1
    # the source writes into the head buffer through its own emitter
    self._src_emitter = _OrderedEmitter(self._buffers[0], self._det)
    # marker-barrier wiring: every emitter announces marker seqs to the
    # emitter CONSUMING its output buffer (throughput-mode ordering)
    chain = [self._src_emitter] + [s.emitter for s in self._stages]
    for up, down in zip(chain, chain[1:]):
      up.downstream = down
    self._src_stats = {"fetch_s": 0.0, "decode_s": 0.0, "items": 0,
                       "out_wait_s": 0.0}
    self.stats["stages"]["src"] = self._src_stats

  def start(self) -> "GraphExecutor":
    for stage in self._stages:
      stage.spawn(self)
    self._start_source()
    self._tuner = _Autotuner(self)
    self._tuner.start()
    return self

  # -- source ----------------------------------------------------------------

  def _start_source(self) -> None:
    src = self._plan._source
    if src[0] == "pending":
      raise ValueError("cannot start a pipeline() template: bind() it to "
                       "a DataFeed first")
    if src[0] == "interleave":
      t = threading.Thread(target=self._source_interleave, args=(src[1],
                                                                 src[2]),
                           daemon=True, name="tos-pipe-src")
    else:
      t = threading.Thread(target=self._source_single, args=(src,),
                           daemon=True, name="tos-pipe-src")
    self._source_threads.append(t)
    t.start()

  def _emit_source(self, seq: int, item) -> bool:
    return self._src_emitter.emit(seq, [item], self._stop_evt,
                                  self._src_stats)

  def _source_single(self, src) -> None:
    try:
      seq = 0
      for item in self._iter_source(src):
        if self._stop_evt.is_set():
          return
        if not self._emit_source(seq, item):
          return
        seq += 1
        self._src_stats["items"] += 1
        if item[0] == "marker" and item[1] is None:
          return
      self._emit_source(seq, ("marker", None))
    except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
      self._fail(e)

  def _iter_source(self, src):
    """Generator of wire items for one (non-interleave) source spec.
    Legacy row lists with inline markers are split so every downstream
    stage sees markers as standalone items."""
    if src[0] == "chunks":
      for obj in src[1]:
        for item in _split_inline_markers(_normalize_source_item(obj)):
          yield item
          if item[0] == "marker" and item[1] is None:
            return
      return
    # ("feed", feed): chunk-granular fetch off the feed's input channel,
    # with the feed's own liveness discipline (worker tracebacks, hub
    # state, liveness_timeout) — datafeed._fetch_chunk is the one fetch
    # implementation
    from tensorflowonspark_tpu import datafeed as datafeed_mod
    feed = src[1]
    stalled_since = time.monotonic()
    while not self._stop_evt.is_set():
      got = datafeed_mod._fetch_chunk(
          feed._queue_in, datafeed_mod.DEFAULT_FETCH_ROWS,
          timeout=_POLL, stats=self._src_stats)
      if got is None:
        feed._check_liveness(stalled_since)
        if feed.done_feeding:       # hub moved to terminating/stopped
          yield ("marker", None)
          return
        continue
      stalled_since = time.monotonic()
      if got[0] == "marker" and got[1] is None:
        feed.done_feeding = True
        yield ("marker", None)
        return
      for item in _split_inline_markers(got):
        if item[0] == "marker" and item[1] is None:
          feed.done_feeding = True
          yield item
          return
        yield item

  def _source_interleave(self, sources: List["Dataset"], cycle: int) -> None:
    """Parallel interleave across sub-sources: up to ``cycle`` reader
    threads fill per-source buffers; this merger thread emits
    round-robin over the ACTIVATION-ordered rotation (deterministic
    mode blocks on the rotation head, so the merged order is a pure
    function of the source contents) or ready-first in throughput
    mode. A sub-source leaves the rotation only once its reader
    finished AND its buffer drained (no timing race can skip it); a
    freed rotation slot activates the next pending source; ONE
    end-of-feed marker is emitted after all sources end."""
    try:
      pending = list(sources)
      rotation: List[Dict] = []

      def _activate():
        while len(rotation) < cycle and pending:
          ds = pending.pop(0)
          slot = {"buf": _Buffer(max(1, _DEFAULT_DEPTH)), "done": False}

          def _reader(ds=ds, slot=slot):
            try:
              for item in self._iter_source(ds._source):
                if self._stop_evt.is_set():
                  return
                if item[0] == "marker" and item[1] is None:
                  break
                while not self._stop_evt.is_set():
                  if slot["buf"].pipe_put(item, timeout=_POLL):
                    break
            except BaseException as e:  # noqa: BLE001 - consumer-side
              self._fail(e)
            finally:
              # set AFTER the last buffered item: done+empty => truly
              # exhausted, so retiring a slot on that pair is race-free
              slot["done"] = True

          t = threading.Thread(target=_reader, daemon=True,
                               name="tos-pipe-interleave")
          slot["thread"] = t
          rotation.append(slot)
          t.start()

      _activate()
      seq = 0
      p = 0
      while not self._stop_evt.is_set():
        if not rotation:
          if pending:
            _activate()
            continue
          self._emit_source(seq, ("marker", None))
          return
        p %= len(rotation)
        scan = (range(p, p + 1) if self._det
                else range(p, p + len(rotation)))
        advanced = False
        for k in scan:
          slot = rotation[k % len(rotation)]
          got = slot["buf"].pipe_get(
              timeout=_POLL if k == p else 0.001)
          if got is _EMPTY:
            if slot["done"] and not len(slot["buf"]):
              rotation.remove(slot)     # exhausted: leave the rotation
              _activate()
              advanced = True
              break
            continue
          if not self._emit_source(seq, got):
            return
          seq += 1
          self._src_stats["items"] += 1
          p = (rotation.index(slot) + 1) % len(rotation)
          advanced = True
          break
        if not advanced:
          continue
    except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
      self._fail(e)

  # -- workers ---------------------------------------------------------------

  def _stage_worker(self, stage: _StageRuntime, idx: int) -> None:
    del idx   # thread-name cosmetics only; retirement is by live count
    stats = stage.stats
    try:
      while not self._stop_evt.is_set():
        if stage.should_retire():
          return    # the autotuner shrank this pool; retire quietly
        t0 = time.perf_counter()
        got = stage.inbuf.pipe_get(timeout=_POLL)
        stats["in_wait_s"] += time.perf_counter() - t0
        if got is _EMPTY:
          continue
        seq, item = got
        t1 = time.perf_counter()
        outputs = stage.body(item)
        stats["busy_s"] += time.perf_counter() - t1
        stats["items"] += 1
        if not stage.emitter.emit(seq, outputs, self._stop_evt, stats):
          return
    except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
      self._fail(e)

  def _fail(self, error: BaseException) -> None:
    if self._error is None:
      self._error = error
    self._stop_evt.set()

  # -- consumer plane --------------------------------------------------------

  def get(self, timeout: float):
    """Next output item (``("batch", payload)`` / ``("end", None)`` /
    raw wire items for transform-only graphs), or ``None`` on timeout.
    Re-raises a worker error."""
    if self._error is not None:
      raise self._error
    got = self._buffers[-1].pipe_get(timeout=timeout)
    if self._error is not None:
      raise self._error
    if got is _EMPTY:
      return None
    _, item = got
    return item

  def batches(self):
    """Generator over assembled batch payloads until end-of-feed. Stops
    the executor when the stream ends (or the consumer closes it)."""
    try:
      while True:
        item = self.get(timeout=1.0)
        if item is None:
          continue
        kind, payload = item
        if kind == "end" or (kind == "marker" and payload is None):
          return
        if kind in ("batch", "data"):
          self._note_delivery(payload)
          yield payload
    finally:
      self.stop()

  def _note_delivery(self, payload) -> None:
    self.stats["batches"] += 1
    n = _payload_rows(payload)
    self.stats["rows"] += n
    if self._obs_m is not None:
      self._obs_m["batches"].inc()
      if n:
        self._obs_m["rows"].inc(n)

  def stats_snapshot(self) -> obs_metrics.StatsSnapshot:
    """Subtraction baseline over the LIVE ``stats`` dict (per-stage
    dicts included) — the one safe way to read steady-state deltas
    while worker threads keep mutating them."""
    return obs_metrics.snapshot_stats(self.stats)

  def stage_summary(self) -> Dict[str, dict]:
    """Per-stage worker/depth/counter view (autotuner decisions land
    here; ``feed_bench --graph`` prints it)."""
    out = {"src": dict(self._src_stats, workers=len(self._source_threads),
                       depth=self._buffers[0].capacity)}
    for stage in self._stages:
      out[stage.name] = dict(stage.stats, workers=stage.target,
                             depth=stage.inbuf.capacity)
    return out

  def stop(self) -> None:
    """Stop every worker and the tuner; buffered items discard."""
    self._stop_evt.set()
    if self._tuner is not None:
      self._tuner.stop()
      # final gauge mirror: a run shorter than one autotune interval
      # must still leave its per-stage totals on the obs wire
      self._tuner._mirror_gauges()
      self._tuner = None
    for t in self._source_threads:
      t.join(timeout=5.0)
    for stage in self._stages:
      for t in stage.threads:
        t.join(timeout=5.0)


def _payload_rows(payload) -> int:
  """Row count of one delivered batch payload (Slab/dict/array/rows)."""
  from tensorflowonspark_tpu.data.readers import Slab
  if isinstance(payload, Slab):
    data = payload.data
    leaf = (next(iter(data.values())) if isinstance(data, dict)
            else data[0] if isinstance(data, tuple) else data)
    return int(leaf.shape[0] * leaf.shape[1]) if hasattr(leaf, "shape") \
        else 0
  if isinstance(payload, dict):
    return len(next(iter(payload.values()))) if payload else 0
  if isinstance(payload, tuple):
    return len(payload[0]) if payload else 0
  if isinstance(payload, chunkcodec.ColumnChunk):
    return payload.n
  try:
    return len(payload)
  except TypeError:
    return 0


# -- the autotuner ------------------------------------------------------------


class _Autotuner(object):
  """Online per-stage parallelism/buffer reallocation (tf.data's
  headline idea, arXiv 2101.12127 §autotuning).

  Every ``TOS_DATA_AUTOTUNE_INTERVAL`` seconds: snapshot-subtract the
  per-stage counters, normalize busy seconds per worker-second
  (utilization), and attribute the bottleneck to the DOMINANT stage —
  the same attribution the obs plane's ``feed_stall`` detector reports,
  used here as the control loop's error signal. One move per pass:

  - a hot (util ≥ 0.5/worker) parallelizable stage gains a worker (up
    to ``TOS_DATA_MAX_WORKERS``), donated by the coldest shrinkable
    pool when one exists;
  - a hot stateful/source stage (map fns can parallelize; shuffle,
    assemble and the source cannot) gets a DEEPER hand-off buffer
    instead (up to ``TOS_DATA_BUFFER_CAP``) so burst skew smooths out;
  - a cold (util < 0.05/worker) multi-worker pool shrinks by one.

  Each move is a structured event: counted (``feed.autotune_moves``),
  ring-buffered on the executor (``autotune_events``), and emitted into
  the obs JSONL via the active recorder (``feed.autotune`` events). The
  pass also mirrors the per-stage gauges (``feed.stage.<name>.*``) the
  detector and ``obs_top`` read — the mirror runs even with autotune
  OFF, so a fixed plan is still observable. Disabled entirely when the
  executor never starts it.
  """

  def __init__(self, executor: GraphExecutor):
    self._ex = executor
    self.interval = max(0.05, _env_float(ENV_DATA_AUTOTUNE_INTERVAL,
                                         _DEFAULT_INTERVAL))
    self._stop_evt = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self._snap = executor.stats_snapshot()
    self._last_t = time.monotonic()
    #: broken passes counted, never raised (the detector-loop invariant)
    self.failures = 0

  def start(self) -> None:
    self._thread = threading.Thread(target=self._run, daemon=True,
                                    name="tos-pipe-tune")
    self._thread.start()

  def stop(self) -> None:
    self._stop_evt.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None

  def _run(self) -> None:
    while not self._stop_evt.wait(self.interval):
      try:
        self.pulse()
      except Exception:  # noqa: BLE001 - the tuner must outlive any
        # single pass bug; a broken pass skips (counted, never raised —
        # the detector-loop invariant) and the pipeline keeps going
        self.failures += 1
        logger.exception("autotune pass failed")

  # one pass, callable directly from tests with a fabricated delta
  def pulse(self) -> Optional[dict]:
    now = time.monotonic()
    dt = max(1e-6, now - self._last_t)
    delta = self._snap.delta()
    self._snap = self._ex.stats_snapshot()
    self._last_t = now
    stages = delta.get("stages", {})
    self._mirror_gauges()
    if not self._ex._autotune:
      return None
    return self._decide(stages, dt)

  def _busy(self, name: str, d: Dict) -> float:
    if name == "src":
      return d.get("fetch_s", 0.0) + d.get("decode_s", 0.0)
    return d.get("busy_s", 0.0)

  def _decide(self, stages: Dict[str, Dict], dt: float) -> Optional[dict]:
    ex = self._ex
    runtimes = {s.name: s for s in ex._stages}
    util = {}
    for name, d in stages.items():
      workers = runtimes[name].target if name in runtimes else 1
      util[name] = self._busy(name, d) / (workers * dt)
    if not util:
      return None
    dominant = max(util, key=util.get)
    move = None
    if util[dominant] >= _HOT_UTIL:
      stage = runtimes.get(dominant)
      if stage is not None and stage.parallelizable \
          and stage.target < ex._max_workers:
        donor = self._coldest(util, runtimes, exclude=dominant)
        if donor is not None:
          donor.shrink()
        stage.grow(ex)
        move = {"action": "add_worker", "stage": dominant,
                "workers": stage.target,
                "donor": donor.name if donor is not None else None}
      else:
        buf = self._inbuf_of(dominant)
        if buf is not None and buf.capacity < ex._buffer_cap:
          buf.set_capacity(min(ex._buffer_cap, buf.capacity * 2))
          move = {"action": "grow_buffer", "stage": dominant,
                  "depth": buf.capacity}
    if move is None:
      donor = self._coldest(util, runtimes)
      if donor is not None:
        donor.shrink()
        move = {"action": "remove_worker", "stage": donor.name,
                "workers": donor.target}
    if move is not None:
      move["util"] = round(util[dominant], 3)
      move["dominant"] = dominant
      self._record(move)
    return move

  def _coldest(self, util, runtimes, exclude=None):
    best, best_u = None, _COLD_UTIL
    for name, u in util.items():
      stage = runtimes.get(name)
      if stage is None or name == exclude or stage.target <= 1:
        continue
      if u < best_u:
        best, best_u = stage, u
    return best

  def _inbuf_of(self, name: str) -> Optional[_Buffer]:
    ex = self._ex
    if name == "src":
      return ex._buffers[0]   # deepen the source's OUT buffer: prefetch
    for stage in ex._stages:
      if stage.name == name:
        return stage.inbuf
    return None

  def _record(self, move: dict) -> None:
    ex = self._ex
    move = dict(move, t=time.time())
    ex.stats["autotune_moves"] += 1
    ex.autotune_events.append(move)
    if ex._obs_m is not None:
      ex._obs_m["moves"].inc()
    rec = ex._rec
    if rec is not None:
      rec.event("feed.autotune",
                **{k: v for k, v in move.items() if k != "t"})
    logger.info("datapipe autotune: %s", move)

  def _mirror_gauges(self) -> None:
    """Mirror live per-stage totals into registry gauges — the wire the
    ``feed_stall`` detector's per-graph-stage attribution and
    ``obs_top``'s ``pipe[...]`` suffix read. Source busy splits into
    the fetch/decode virtual stages so fetch-dominant windows stay
    attributable."""
    m = self._ex._obs_m
    if m is None:
      return
    reg = m["reg"]
    summary = self._ex.stage_summary()
    for name, d in summary.items():
      if name == "src":
        # workers/depth ride the SAME virtual-stage names as the busy
        # gauges so readers keyed on ``*.busy_s`` (obs_top) can pair
        # them — a grow_buffer move on the source shows as fetch/decode
        # depth, not under an unrenderable ``src``
        for virt, busy in (("fetch", d.get("fetch_s", 0.0)),
                           ("decode", d.get("decode_s", 0.0))):
          reg.gauge("feed.stage.%s.busy_s" % virt).set(busy)
          reg.gauge("feed.stage.%s.workers" % virt).set(d["workers"])
          reg.gauge("feed.stage.%s.depth" % virt).set(d["depth"])
      else:
        reg.gauge("feed.stage.%s.busy_s" % name).set(d.get("busy_s", 0.0))
        reg.gauge("feed.stage.%s.workers" % name).set(d["workers"])
        reg.gauge("feed.stage.%s.depth" % name).set(d["depth"])


# -- the declarative graph ----------------------------------------------------


class Dataset(object):
  """A lazy, declarative transform graph over columnar chunk streams.

  Compose sources with transforms; nothing runs until :meth:`batches`
  / :meth:`chunks` / :meth:`start`. Every composition returns a NEW
  ``Dataset`` (the graph is immutable, tf.data-style)::

      ds = (Dataset.from_feed(feed)
              .map(lambda x, y: (x / 255.0, y), columnar=True)
              .shuffle(4096, seed=run_seed)
              .slab(batch_size, unroll)
              .prefetch(4))
      for slab in device_prefetch(ds.batches(), size=2):
          state, losses = loop(state, slab)

  ``deterministic=True`` (default) pins element order — the graph then
  composes with the fused train loop's bit-identical-trajectory
  contract (``from_feed(feed).slab(B, K)`` ≡
  ``data.readers.slab_batches(feed, B, K)`` batch for batch).
  """

  def __init__(self, source, ops: Optional[List] = None,
               columns: Optional[List[str]] = None,
               train_mode: bool = True,
               depths: Optional[Dict[int, int]] = None):
    self._source = source
    self._ops = list(ops or [])
    self._columns = columns
    self._train_mode = train_mode
    self._depths = dict(depths or {})

  # -- sources ---------------------------------------------------------------

  @classmethod
  def from_feed(cls, feed) -> "Dataset":
    """Source over a :class:`datafeed.DataFeed`'s input channel.

    The graph REPLACES the feed's own fixed-depth ``_FetchPipeline``
    (an already-started one is retired) — do not consume the feed via
    ``next_batch*`` while a graph over it is running. Column names come
    from the feed's ``input_mapping`` and marker semantics from its
    ``train_mode``; end-of-feed sets ``feed.done_feeding`` so
    ``should_stop()`` keeps its meaning.
    """
    feed._stop_pipeline()
    return cls(("feed", feed), columns=feed.input_tensors,
               train_mode=feed.train_mode)

  @classmethod
  def pipeline(cls) -> "Dataset":
    """DRIVER-side graph template with a pending source.

    Compose transforms on it, call :meth:`split_pushdown` to carve off
    the feeder segment for ``cluster.run(feed_segment=...)``, then
    :meth:`bind` the consumer remainder to the executor's
    :class:`datafeed.DataFeed` inside the user main fn. A pending graph
    cannot start — :meth:`bind` it first."""
    return cls(("pending", None))

  def bind(self, feed) -> "Dataset":
    """Bind a pending graph (:meth:`pipeline`) to a live feed: the
    :meth:`from_feed` source plus THIS graph's ops. Column names and
    marker semantics come from the feed, exactly as ``from_feed``."""
    if self._source[0] != "pending":
      raise ValueError("bind() is for pipeline() templates; this graph "
                       "already has a %r source" % (self._source[0],))
    feed._stop_pipeline()
    out = Dataset(("feed", feed), self._ops, feed.input_tensors,
                  feed.train_mode, self._depths)
    return out

  def split_pushdown(self):
    """Split this graph at the first non-pushable stage.

    Returns ``(feeder_segment, consumer_dataset)``. Pushable stages are
    the LEADING stateless ``map``/``filter`` ops — ``shuffle``/``batch``/
    ``slab`` and everything after stay consumer-side, and ``interleave``
    sources never push (the merge point is the consumer). Returns
    ``(None, self)`` when nothing pushes (including when
    ``TOS_FEED_PUSHDOWN=0`` disables the split)."""
    if os.environ.get(ENV_FEED_PUSHDOWN, "1").strip().lower() in (
        "0", "false", "off"):
      return None, self
    if self._source[0] == "interleave":
      return None, self
    k = 0
    for op in self._ops:
      if op[0] in ("map", "filter"):
        k += 1
      else:
        break
    if k == 0:
      return None, self
    segment = FeederSegment([tuple(op) for op in self._ops[:k]])
    depths: Dict[int, int] = {}
    for i, d in self._depths.items():
      if i < 0:
        depths[i] = max(d, depths.get(i, 0))
      elif i < k:
        # a prefetch declared after a pushed stage now pads the buffer
        # after the consumer-side source instead
        depths[-1] = max(d, depths.get(-1, 0))
      else:
        depths[i - k] = d
    rest = Dataset(self._source, self._ops[k:], self._columns,
                   self._train_mode, depths)
    return segment, rest

  @classmethod
  def from_chunks(cls, chunks, columns: Optional[List[str]] = None,
                  train_mode: bool = True) -> "Dataset":
    """Source over an iterable of chunks: ``ColumnChunk``\\ s, row
    lists, ``Marker``\\ s (partition boundaries) and a final ``None``
    (end-of-feed; appended implicitly when the iterable just ends)."""
    return cls(("chunks", chunks), columns=columns, train_mode=train_mode)

  @classmethod
  def interleave(cls, sources: Sequence["Dataset"],
                 cycle: Optional[int] = None) -> "Dataset":
    """Parallel interleave across ``sources`` (each a PURE source —
    ``from_chunks``/``from_feed`` with no transforms; transforms
    compose after the merge): up to ``cycle`` sources are read
    concurrently, chunks merged round-robin in source order under
    ``deterministic=True`` or ready-first in throughput mode. One
    end-of-feed marker is emitted after ALL sources end; per-source
    ``EndPartition`` markers ride the merge in stream position."""
    sources = list(sources)
    if not sources:
      raise ValueError("interleave needs at least one source")
    for ds in sources:
      if not isinstance(ds, Dataset):
        raise TypeError("interleave sources must be Datasets")
      if ds._ops:
        raise ValueError(
            "interleave sources must be pure sources (compose transforms "
            "AFTER the interleave; source %r carries ops)" % (ds,))
    cycle = max(1, int(cycle if cycle is not None else len(sources)))
    first = sources[0]
    return cls(("interleave", sources, cycle), columns=first._columns,
               train_mode=first._train_mode)

  # -- transforms ------------------------------------------------------------

  def _extended(self, op) -> "Dataset":
    if self._terminal() is not None:
      raise ValueError("batch()/slab() is terminal: no transforms may "
                       "follow it (prefetch() excepted)")
    return Dataset(self._source, self._ops + [op], self._columns,
                   self._train_mode, self._depths)

  def _terminal(self):
    for op in self._ops:
      if op[0] in ("batch", "slab"):
        return op
    return None

  def map(self, fn: Callable, columnar: bool = False) -> "Dataset":
    """Apply ``fn`` to every element. ``columnar=True``: ``fn`` is
    VECTORIZED — called once per chunk with the column arrays
    (``fn(*cols) -> col | (cols...)``), no per-row Python loop.
    ``columnar=False``: ``fn(row) -> row`` per row; homogeneous results
    re-columnarize so downstream stages stay on the fast path. Markers
    pass through untouched."""
    return self._extended(("map", fn, bool(columnar)))

  def filter(self, pred: Callable, columnar: bool = False) -> "Dataset":
    """Keep elements where ``pred`` holds. ``columnar=True``:
    ``pred(*cols) -> bool mask`` over the chunk (vectorized row
    selection — one fancy-index per column). ``columnar=False``:
    ``pred(row) -> bool`` per row."""
    return self._extended(("filter", pred, bool(columnar)))

  def shuffle(self, buffer_rows: int, seed: int = 0) -> "Dataset":
    """Streaming row-granular shuffle holding ``buffer_rows`` rows
    (vectorized gather on the columnar path). Deterministic per
    ``seed`` + element arrival order; the buffer flushes at markers so
    rows never cross an ``EndPartition``/end-of-feed boundary."""
    return self._extended(("shuffle", int(buffer_rows), int(seed)))

  def batch(self, batch_size: int, dtype=None) -> "Dataset":
    """Terminal: assemble ``batch_size``-row host batches
    (``feed_batches`` semantics: partial final batch at end-of-feed,
    ``EndPartition`` skip/boundary per train/inference mode, empty
    batches skipped)."""
    return self._extended(("batch", int(batch_size), 1, dtype))

  def slab(self, batch_size: int, unroll: int, dtype=None) -> "Dataset":
    """Terminal: assemble ``[unroll, batch_size, ...]``
    :class:`data.readers.Slab`\\ s for the fused train loop
    (``slab_batches`` semantics: short stretches split into the same
    per-step batches, which keeps the fused trajectory bit-identical
    through the graph)."""
    return self._extended(("slab", int(batch_size), int(unroll), dtype))

  def prefetch(self, depth: int) -> "Dataset":
    """Set the hand-off buffer depth AFTER the last declared stage (the
    autotuner may still deepen it further, up to
    ``TOS_DATA_BUFFER_CAP``)."""
    out = Dataset(self._source, self._ops, self._columns, self._train_mode,
                  self._depths)
    out._depths[len(out._ops) - 1] = max(1, int(depth))
    return out

  # -- execution -------------------------------------------------------------

  def start(self, deterministic: bool = True,
            autotune: Optional[bool] = None) -> GraphExecutor:
    """Materialize and start the executor (callers own ``stop()``)."""
    if self._source[0] == "pending":
      raise ValueError("cannot start a pipeline() template: bind() it to "
                       "a DataFeed first")
    return GraphExecutor(self, deterministic=deterministic,
                         autotune=autotune).start()

  def batches(self, deterministic: bool = True,
              autotune: Optional[bool] = None):
    """Run the graph and yield assembled batch payloads (requires a
    ``batch()``/``slab()`` terminal). The generator stops the executor
    when the stream ends or the caller closes it."""
    if self._terminal() is None:
      raise ValueError("batches() needs a batch()/slab() terminal; use "
                       "chunks() for transform-only graphs")
    ex = self.start(deterministic=deterministic, autotune=autotune)
    return ex.batches()

  def chunks(self, deterministic: bool = True,
             autotune: Optional[bool] = None):
    """Run a transform-only graph and yield normalized wire items
    (``("data", ColumnChunk|rows)`` / ``("marker", m)``) until
    end-of-feed."""
    if self._terminal() is not None:
      raise ValueError("chunks() is for transform-only graphs; this one "
                       "has a batch()/slab() terminal — use batches()")
    ex = self.start(deterministic=deterministic, autotune=autotune)

    def _gen():
      try:
        while True:
          item = ex.get(timeout=1.0)
          if item is None:
            continue
          if item[0] == "marker" and item[1] is None:
            return
          yield item
      finally:
        ex.stop()

    return _gen()
