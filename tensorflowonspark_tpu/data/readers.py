"""FILES-mode input pipeline: sharded readers with device prefetch.

The reference's FILES/TENSORFLOW input mode had each node build its own
tf.data pipeline from HDFS shards (reference
examples/mnist/keras/mnist_tf_ds.py). This module is that capability for
the JAX path: deterministic file sharding per node, a TFRecord example
reader, batch assembly, and a double-buffered host→device prefetch
iterator so input never stalls the accelerator.
"""

import itertools
import logging
from typing import (Any, Callable, Iterable, Iterator, List, NamedTuple,
                    Optional, Sequence)

logger = logging.getLogger(__name__)


class Slab(NamedTuple):
  """``unroll`` host batches stacked into one ``[K, B, ...]`` pytree.

  The transport unit of the fused train loop
  (``parallel.sharding.make_train_loop``): one slab = one jitted
  ``lax.scan`` dispatch of K optimizer steps. A NamedTuple, so it IS a
  jax pytree — ``device_prefetch`` / ``jax.device_put`` map straight
  through it. ``data`` holds the stacked columns (an array, or a dict of
  arrays under an input_mapping).
  """
  data: Any


def shard_files(pattern_or_paths, num_shards: int, shard_index: int,
                ) -> List[str]:
  """Deterministically assign files to one of ``num_shards`` readers.

  Usage inside a main fn: ``shard_files(pattern, ctx.num_workers,
  ctx.task_index)`` — every worker gets a disjoint, stable subset.
  Remote patterns (``gs://bucket/data/part-*``) list through fsspec and
  return scheme-qualified paths (parity: reference readers listed shards
  through Hadoop's FS layer, e.g. TFNode.hdfs_path call sites).
  """
  from tensorflowonspark_tpu.data import fs
  if isinstance(pattern_or_paths, str):
    paths = sorted(fs.glob_files(pattern_or_paths))
  else:
    paths = sorted(pattern_or_paths)
  if not paths:
    raise FileNotFoundError("no input files match %r" % (pattern_or_paths,))
  if num_shards <= 1:
    return paths
  return paths[shard_index::num_shards]


def read_tfrecord_examples(paths: Sequence[str], schema=None,
                           repeat: bool = False) -> Iterator:
  """Iterate decoded rows (tuples per schema) or raw feature dicts from
  TFRecord files."""
  from tensorflowonspark_tpu.data import dfutil, example_codec, tfrecord

  def _once():
    for path in paths:
      for record in tfrecord.TFRecordReader(path):
        if schema is not None:
          yield dfutil.from_example(record, schema)
        else:
          yield example_codec.decode_example(record)

  if not repeat:
    yield from _once()
    return
  if not paths:
    # an empty shard (num_shards > file count) must not busy-spin forever;
    # synchronous multi-worker jobs should size shards to workers instead
    raise ValueError(
        "repeat=True with an empty path list would spin forever; this "
        "worker's file shard is empty (more workers than files?)")
  while True:
    yield from _once()


def shuffled(rows: Iterable, buffer_size: int, seed: int = 0) -> Iterator:
  """Streaming shuffle buffer (parity role: ``tf.data.Dataset.shuffle``,
  which the reference's FILES-mode examples applied to their record
  streams): holds ``buffer_size`` rows and yields a uniformly-sampled
  one as each new row arrives, draining the buffer shuffled at end.
  Deterministic per ``seed`` — combine with the worker's ``task_index``
  for distinct per-shard orders.
  """
  import random
  if buffer_size <= 1:
    yield from rows
    return
  rnd = random.Random(seed)
  buf = []
  for row in rows:
    if len(buf) < buffer_size:
      buf.append(row)
      continue
    i = rnd.randrange(buffer_size)
    yield buf[i]
    buf[i] = row
  rnd.shuffle(buf)
  yield from buf


def batched(rows: Iterable, batch_size: int, drop_remainder: bool = True,
            collate: Optional[Callable] = None) -> Iterator:
  """Group rows into batches; ``collate`` maps a list of rows to arrays
  (default: numpy-stack each column)."""
  import numpy as np

  def _default_collate(batch):
    if isinstance(batch[0], (tuple, list)):
      return tuple(np.asarray([row[i] for row in batch])
                   for i in range(len(batch[0])))
    return np.asarray(batch)

  collate = collate or _default_collate
  it = iter(rows)
  while True:
    batch = list(itertools.islice(it, batch_size))
    if not batch:
      return
    if len(batch) < batch_size and drop_remainder:
      return
    yield collate(batch)


def feed_batches(feed, batch_size: int, dtype=None) -> Iterator:
  """Host-batch generator over a :class:`datafeed.DataFeed`.

  Yields ``feed.next_batch_arrays(batch_size)`` results (arrays on the
  columnar fast path, one per batch) until the feed's end-of-feed marker,
  skipping the empty tail batch — the canonical source for
  :func:`device_prefetch` / ``datafeed.prefetch_to_device``::

      for x in device_prefetch(feed_batches(feed, B), size=2):
          state, loss = step(state, x)

  With the feed's own fetch pipeline on (``TOS_FEED_PIPELINE``), hub RPC +
  decode, host→device transfer, and the jitted step all overlap.
  """
  while not feed.should_stop():
    batch = feed.next_batch_arrays(batch_size, dtype=dtype)
    n = len(next(iter(batch.values()))) if isinstance(batch, dict) \
        else len(batch)
    if n:
      yield batch


def slab_batches(feed, batch_size: int, unroll: Optional[int] = None,
                 dtype=None) -> Iterator:
  """Slab generator over a :class:`datafeed.DataFeed` — the fused train
  loop's canonical source.

  Yields :class:`Slab`\\ s of ``unroll`` stacked ``batch_size`` batches
  (one columnar assembly + ONE concatenate per column for the whole
  slab, reshaped for free — ``DataFeed.next_slab_arrays``) until the
  stream can no longer fill a whole slab; the partial tail (end-of-feed,
  or a short stretch at an ``EndPartition`` boundary) degrades to plain
  per-batch yields, which ride the loop's per-step jit entry — batch
  ORDER is identical to ``feed_batches(feed, batch_size)``, which is
  what makes the fused trajectory bit-identical to the per-step one.
  Compose with :func:`device_prefetch` (default ``sharding=None`` —
  mixed slab/batch items take plain ``device_put``; the jitted loop's
  ``in_shardings`` place them) so slab k+1 transfers under slab k's
  compute::

      loop = make_train_loop(loss_fn, mesh, sharding, unroll=K)
      for item in device_prefetch(slab_batches(feed, B, K), size=2):
          state, losses = loop(state, item)

  ``unroll=None`` reads ``TOS_TRAIN_UNROLL`` (1 = plain
  :func:`feed_batches` semantics, wrapped item-for-item).
  """
  from tensorflowonspark_tpu.parallel.sharding import resolve_unroll
  unroll = resolve_unroll(unroll)
  if unroll <= 1:
    yield from feed_batches(feed, batch_size, dtype=dtype)
    return
  while not feed.should_stop():
    got = feed.next_slab_arrays(batch_size, unroll, dtype=dtype)
    if isinstance(got, Slab):
      yield got
      continue
    # partial tail: split into the SAME per-step batches feed_batches
    # would have produced (full ones first, short remainder last)
    if isinstance(got, dict):
      n = len(next(iter(got.values()))) if got else 0
      for i in range(0, n, batch_size):
        yield {k: v[i:i + batch_size] for k, v in got.items()}
    else:
      for i in range(0, len(got), batch_size):
        yield got[i:i + batch_size]


def device_prefetch(batches: Iterable, size: int = 2,
                    sharding=None) -> Iterator:
  """Double-buffered host→device transfer (parity role: tf.data prefetch).

  Keeps at most ``size`` batches device-resident: the async device_put
  of batch N+1 overlaps the compute consuming batch N, hiding
  host-to-HBM transfer latency. ``size`` clamps to >= 1, where it
  degrades to plain per-batch device_put; with a blocking source the
  first yield happens after ``size`` batches have staged, never more.
  """
  import collections
  import jax

  size = max(1, size)

  def _put(batch):
    if sharding is not None:
      return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
    return jax.tree.map(jax.device_put, batch)

  queue = collections.deque()
  for batch in batches:
    queue.append(_put(batch))
    if len(queue) >= size:
      yield queue.popleft()
  while queue:
    yield queue.popleft()
