"""fsspec-backed file IO: one open/glob surface for local and remote storage.

The reference ran its whole data plane on cluster storage — every example
read HDFS via Hadoop's FS layer (reference TFNode.py:32-67 exists because of
it, and TFRecord IO went through it in reference dfutil.py:39,63). The TPU
build targets GCS-first storage: this module routes any ``scheme://`` URI
through fsspec (gcsfs for ``gs://``, plus s3/hdfs/memory/... whatever the
environment provides) while keeping plain paths on fast builtin IO, so
FILES-mode training can read and write cluster storage, not just local disk.

Streamed reads/writes: fsspec file objects buffer remote blocks, so TFRecord
framing works record-at-a-time without downloading whole files.
"""

import glob as _glob
import logging
import os
import re

from tensorflowonspark_tpu.utils import paths as _paths

logger = logging.getLogger(__name__)

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")


def is_remote(path: str) -> bool:
  """True when ``path`` names a non-local filesystem — ANY ``scheme://``
  URI except ``file://`` (gs://, s3://, hdfs://, memory://, ...); fsspec
  resolves the backend, so no scheme allowlist here."""
  return (isinstance(path, str) and bool(_SCHEME_RE.match(path))
          and not path.startswith("file://"))


def _fsspec():
  import fsspec
  return fsspec


def open_file(path: str, mode: str = "rb"):
  """Open ``path`` for streamed IO; remote schemes go through fsspec."""
  if is_remote(path):
    fs, fpath = _fsspec().core.url_to_fs(path)
    if "w" in mode or "a" in mode:
      parent = fpath.rsplit("/", 1)[0] if "/" in fpath else ""
      if parent:
        # object stores don't need it; real FS backends (hdfs, local relays)
        # do — mirrors open()'s caller expectation that dirs exist only
        # locally, where writers already create them
        try:
          fs.makedirs(parent, exist_ok=True)
        except Exception:  # noqa: BLE001 - best-effort, open will raise
          pass
    return fs.open(fpath, mode)
  return open(_paths.strip_scheme(path), mode)


def glob_files(pattern: str):
  """Expand a glob pattern into concrete paths, preserving the scheme.

  Remote patterns return fully-qualified URIs (``gs://bucket/part-0000``) so
  downstream readers route back through fsspec; local patterns behave like
  ``glob.glob``.
  """
  if is_remote(pattern):
    fs, fpattern = _fsspec().core.url_to_fs(pattern)
    return [fs.unstrip_protocol(p) for p in fs.glob(fpattern)]
  return _glob.glob(_paths.strip_scheme(pattern))


def file_size(path: str) -> int:
  """Size in bytes (remote schemes ask the backend, no download)."""
  if is_remote(path):
    fs, fpath = _fsspec().core.url_to_fs(path)
    return int(fs.size(fpath))
  return os.path.getsize(_paths.strip_scheme(path))


def exists(path: str) -> bool:
  if is_remote(path):
    fs, fpath = _fsspec().core.url_to_fs(path)
    return fs.exists(fpath)
  return os.path.exists(_paths.strip_scheme(path))


def makedirs(path: str, exist_ok: bool = True) -> None:
  if is_remote(path):
    fs, fpath = _fsspec().core.url_to_fs(path)
    fs.makedirs(fpath, exist_ok=exist_ok)
    return
  os.makedirs(_paths.strip_scheme(path), exist_ok=exist_ok)


def listdir(path: str):
  """Names (not full paths) under ``path``."""
  if is_remote(path):
    fs, fpath = _fsspec().core.url_to_fs(path)
    return sorted(p.rstrip("/").rsplit("/", 1)[-1]
                  for p in fs.ls(fpath, detail=False))
  return sorted(os.listdir(_paths.strip_scheme(path)))
