"""TFRecord file reader/writer.

Primary path: the native C++ codec (native/tfrecord_codec.cpp, built to
``_tfrecord_native.so``, auto-compiled on first use when a toolchain is
available). Fallback: a pure-Python implementation of the same masked-CRC32C
framing so the format works everywhere.

This is the JVM-free replacement for the tensorflow-hadoop jar the reference
required for all TFRecord interop (reference dfutil.py:39,63).
"""

import ctypes
import logging
import os
import struct
import subprocess
from typing import Iterator

logger = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "_tfrecord_native.so")
_SRC_PATH = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                         "native", "tfrecord_codec.cpp")
_lib = None
_lib_tried = False


def _load_native():
  """Load (building if necessary) the native codec; None if unavailable."""
  global _lib, _lib_tried
  if _lib_tried:
    return _lib
  _lib_tried = True
  if not os.path.exists(_SO_PATH) and os.path.exists(_SRC_PATH):
    for extra in (["-msse4.2"], []):
      try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17"] + extra +
            ["-o", _SO_PATH, _SRC_PATH],
            check=True, capture_output=True, timeout=120)
        break
      except (OSError, subprocess.SubprocessError) as e:
        logger.debug("native codec build attempt failed: %s", e)
  if os.path.exists(_SO_PATH):
    try:
      lib = ctypes.CDLL(_SO_PATH)
      lib.tos_writer_open.restype = ctypes.c_void_p
      lib.tos_writer_open.argtypes = [ctypes.c_char_p]
      lib.tos_writer_write.restype = ctypes.c_int
      lib.tos_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64]
      lib.tos_writer_close.argtypes = [ctypes.c_void_p]
      lib.tos_reader_open.restype = ctypes.c_void_p
      lib.tos_reader_open.argtypes = [ctypes.c_char_p]
      lib.tos_reader_next.restype = ctypes.c_int64
      lib.tos_reader_next.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.POINTER(
                                          ctypes.c_uint8))]
      lib.tos_reader_close.argtypes = [ctypes.c_void_p]
      lib.tos_masked_crc32c.restype = ctypes.c_uint32
      lib.tos_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
      _lib = lib
      logger.info("native TFRecord codec loaded")
    except OSError as e:
      logger.warning("failed to load native codec: %s", e)
  return _lib


def native_available() -> bool:
  return _load_native() is not None


# --- pure-Python CRC32C (fallback path) -------------------------------------

_CRC_TABLE = None


def _crc_table():
  global _CRC_TABLE
  if _CRC_TABLE is None:
    table = []
    for i in range(256):
      c = i
      for _ in range(8):
        c = (0x82F63B78 ^ (c >> 1)) if c & 1 else (c >> 1)
      table.append(c)
    _CRC_TABLE = table
  return _CRC_TABLE


def _crc32c_py(data: bytes) -> int:
  table = _crc_table()
  crc = 0xFFFFFFFF
  for b in data:
    crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
  return crc ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
  lib = _load_native()
  if lib is not None:
    return lib.tos_masked_crc32c(data, len(data))
  crc = _crc32c_py(data)
  return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --- public API -------------------------------------------------------------


class TFRecordWriter(object):
  """Write records to a TFRecord file (local disk or any fsspec scheme).

  Local paths use the native codec when available; remote URIs
  (``gs://...``) stream through fsspec with the pure-Python framing — the
  capability the reference got from the tensorflow-hadoop jar writing
  straight to HDFS (reference dfutil.py:29-41).
  """

  def __init__(self, path: str):
    from tensorflowonspark_tpu.data import fs
    self.path = path
    lib = _load_native() if not fs.is_remote(path) else None
    self._lib = lib
    if lib is not None:
      from tensorflowonspark_tpu.utils import paths as _paths
      self._handle = lib.tos_writer_open(_paths.strip_scheme(path).encode())
      if not self._handle:
        raise OSError("cannot open %s for writing" % path)
      self._file = None
    else:
      self._handle = None
      self._file = fs.open_file(path, "wb")

  def write(self, record: bytes) -> None:
    if self._handle is not None:
      if self._lib.tos_writer_write(self._handle, record, len(record)):
        raise OSError("write failed on %s" % self.path)
    else:
      length = struct.pack("<Q", len(record))
      self._file.write(length)
      self._file.write(struct.pack("<I", masked_crc(length)))
      self._file.write(record)
      self._file.write(struct.pack("<I", masked_crc(record)))

  def close(self) -> None:
    if self._handle is not None:
      self._lib.tos_writer_close(self._handle)
      self._handle = None
    elif self._file:
      self._file.close()
      self._file = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


class TFRecordReader(object):
  """Iterate records of a TFRecord file (local disk or any fsspec scheme).

  Remote URIs stream record-at-a-time through fsspec's buffered reads —
  whole files are never downloaded up front.
  """

  def __init__(self, path: str):
    from tensorflowonspark_tpu.data import fs
    self.path = path
    lib = _load_native() if not fs.is_remote(path) else None
    self._lib = lib
    if lib is not None:
      from tensorflowonspark_tpu.utils import paths as _paths
      self._handle = lib.tos_reader_open(_paths.strip_scheme(path).encode())
      if not self._handle:
        raise OSError("cannot open %s" % path)
      self._file = None
    else:
      self._handle = None
      self._file = fs.open_file(path, "rb")

  def __iter__(self) -> Iterator[bytes]:
    return self

  def __next__(self) -> bytes:
    if self._handle is not None:
      out = ctypes.POINTER(ctypes.c_uint8)()
      n = self._lib.tos_reader_next(self._handle, ctypes.byref(out))
      if n == -1:
        self.close()
        raise StopIteration
      if n == -2:
        self.close()
        raise IOError("corrupt TFRecord in %s" % self.path)
      return ctypes.string_at(out, n)
    header = self._file.read(12)
    if len(header) == 0:
      self.close()
      raise StopIteration
    if len(header) < 12:
      self.close()
      raise IOError("truncated TFRecord header in %s" % self.path)
    (length,), (len_crc,) = struct.unpack("<Q", header[:8]), \
        struct.unpack("<I", header[8:])
    if masked_crc(header[:8]) != len_crc:
      self.close()
      raise IOError("corrupt TFRecord length crc in %s" % self.path)
    data = self._file.read(length)
    crc_raw = self._file.read(4)
    if len(data) < length or len(crc_raw) < 4:
      self.close()
      raise IOError("truncated TFRecord data in %s" % self.path)
    if masked_crc(data) != struct.unpack("<I", crc_raw)[0]:
      self.close()
      raise IOError("corrupt TFRecord data in %s" % self.path)
    return data

  def close(self) -> None:
    if self._handle is not None:
      self._lib.tos_reader_close(self._handle)
      self._handle = None
    elif self._file:
      self._file.close()
      self._file = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
