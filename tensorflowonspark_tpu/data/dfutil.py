"""Rows ↔ TFRecord/tf.Example conversion with schema inference.

Capability parity with the reference's ``dfutil.py``
(/root/reference/tensorflowonspark/dfutil.py): ``save_as_tfrecords`` /
``load_tfrecords`` round-trip partitioned rows through TFRecord files,
``infer_schema`` reads the first record with a ``binary_features`` hint to
disambiguate bytes vs string (:134-168), ``to_example``/``from_example``
map dtypes onto Int64List/FloatList/BytesList (:84-131,171-212), and a
loaded-path registry mirrors ``isLoadedDF`` (:15-26). Engine-agnostic: a
"dataframe" here is (partitions, Schema), where partitions are lists of row
tuples ordered by schema fields.
"""

import glob
import logging
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tensorflowonspark_tpu.data import example_codec, tfrecord
from tensorflowonspark_tpu.data.schema import Field, Schema

logger = logging.getLogger(__name__)

# paths loaded through load_tfrecords, with their schemas — so pipelines can
# skip re-conversion (parity: dfutil.isLoadedDF)
_loaded_paths: Dict[str, Schema] = {}


def _path_key(path: str) -> str:
  from tensorflowonspark_tpu.data import fs
  return path if fs.is_remote(path) else os.path.abspath(path)


def is_loaded_path(path: str) -> bool:
  return _path_key(path) in _loaded_paths


def to_example(row: Sequence, schema: Schema) -> bytes:
  """Encode one row (ordered per schema) as a serialized tf.train.Example."""
  features = {}
  for field, value in zip(schema.fields, row):
    values = list(value) if field.is_array else [value]
    if field.dtype in ("int", "long", "boolean"):
      features[field.name] = [int(v) for v in values]
    elif field.dtype in ("float", "double"):
      features[field.name] = [float(v) for v in values]
    elif field.dtype == "string":
      features[field.name] = [v.encode("utf-8") if isinstance(v, str) else
                              bytes(v) for v in values]
    elif field.dtype == "binary":
      features[field.name] = [bytes(v) for v in values]
    else:
      raise TypeError("unsupported field type %r" % field.dtype)
  return example_codec.encode_example(features)


def from_example(data: bytes, schema: Schema) -> Tuple:
  """Decode a serialized Example into a row tuple ordered per schema."""
  feats = example_codec.decode_example(data)
  row = []
  for field in schema.fields:
    values = feats.get(field.name, [])
    if field.dtype in ("int", "long"):
      values = [int(v) for v in values]
    elif field.dtype == "boolean":
      values = [bool(v) for v in values]
    elif field.dtype in ("float", "double"):
      values = [float(v) for v in values]
    elif field.dtype == "string":
      values = [v.decode("utf-8") if isinstance(v, bytes) else str(v)
                for v in values]
    elif field.dtype == "binary":
      values = [bytes(v) for v in values]
    row.append(list(values) if field.is_array else
               (values[0] if values else None))
  return tuple(row)


def infer_schema(example_bytes: bytes,
                 binary_features: Optional[Set[str]] = None) -> Schema:
  """Infer a Schema from one serialized Example.

  ``binary_features`` marks BytesList features to type as ``binary`` rather
  than ``string`` — the wire format cannot distinguish them (parity:
  reference dfutil.py:134-168). Multi-value features become arrays.
  """
  binary_features = binary_features or set()
  feats = example_codec.decode_example(example_bytes)
  fields = []
  for name in sorted(feats):
    values = feats[name]
    if values and isinstance(values[0], bytes):
      dtype = "binary" if name in binary_features else "string"
    elif values and isinstance(values[0], float):
      dtype = "float"
    else:
      dtype = "long"
    fields.append(Field(name, dtype, is_array=len(values) > 1))
  return Schema(tuple(fields))


def save_as_tfrecords(partitions: Sequence[Iterable], schema: Schema,
                      output_dir: str, engine=None) -> List[str]:
  """Write one ``part-NNNNN.tfrecord`` file per partition.

  With an engine, partitions are written by the executors in parallel
  (parity: reference saveAsNewAPIHadoopFile writing FROM executors,
  dfutil.py:29-41) and the driver ships only partition HANDLES: a
  partition may be a zero-arg callable returning an iterable, in which
  case rows are produced on the executor and the driver allocates O(1)
  memory regardless of dataset size. Plain lists still work (and are
  pickled whole, fine for small data). ``output_dir`` may be a remote URI
  (``gs://...``) — writers stream through fsspec.
  """
  from tensorflowonspark_tpu.data import fs
  fs.makedirs(output_dir, exist_ok=True)
  remote = fs.is_remote(output_dir)
  # Handle recipe for O(1) driver memory with an engine:
  #   parts, schema = load_tfrecords(path, lazy=True)      # or your own
  #   parts = [lambda f=f: read_rows(f) for f in files]    # callables
  #   save_as_tfrecords(parts, schema, out, engine=engine)
  # Callables resolve ON the executor; generators cannot (cloudpickle
  # rejects them) and are materialized driver-side with a warning.

  def _part_path(index: int) -> str:
    name = "part-%05d.tfrecord" % index
    return (output_dir.rstrip("/") + "/" + name) if remote \
        else os.path.join(output_dir, name)

  def _write_partition(index: int, rows) -> str:
    path = _part_path(index)
    if callable(rows):
      rows = rows()
    with tfrecord.TFRecordWriter(path) as w:
      for row in rows:
        w.write(to_example(row, schema))
    return path

  if engine is None:
    return [_write_partition(i, p) for i, p in enumerate(partitions)]

  def _task(it):
    out = []
    for index, rows in it:
      out.append(_write_partition(index, rows))
    return out

  # one engine-partition per output file; callables (or small lists) ship
  # to the executor, which produces the rows itself — never the driver.
  # O(#partitions) handles on the driver, never O(rows). One-shot
  # iterators/generators can't cross the process boundary (cloudpickle
  # rejects generators) — those alone are materialized here.
  def _shippable(i, p):
    if callable(p) or isinstance(p, (list, tuple)):
      return p
    logger.warning(
        "save_as_tfrecords: partition %d is a one-shot iterator; "
        "materializing it on the DRIVER (O(partition) driver memory). "
        "Ship a zero-arg callable (e.g. load_tfrecords(lazy=True) "
        "handles) to produce rows executor-side instead.", i)
    return list(p)

  indexed = [[(i, _shippable(i, p))] for i, p in enumerate(partitions)]
  return sorted(engine.map_partitions(indexed, _task))


def _list_tfrecord_files(path: str) -> List[str]:
  from tensorflowonspark_tpu.data import fs
  if fs.is_remote(path):
    base = path.rstrip("/")
    files = sorted(fs.glob_files(base + "/*.tfrecord")) or \
        sorted(fs.glob_files(base + "/part-*")) or \
        sorted(fs.glob_files(path))
  elif os.path.isdir(path):
    files = sorted(glob.glob(os.path.join(path, "*.tfrecord"))) or \
        sorted(glob.glob(os.path.join(path, "part-*")))
  elif os.path.exists(path):
    files = [path]
  else:
    files = sorted(glob.glob(path))
  if not files:
    raise FileNotFoundError("no TFRecord files at %r" % path)
  return files


def _lazy_file_reader(files: List[str], schema: Schema):
  """A zero-arg callable streaming decoded rows of ``files`` — the lazy
  partition-handle format save_as_tfrecords and the cluster feeders
  (node._materialize_partition) resolve ON the executor."""
  def _read():
    return (from_example(record, schema)
            for f in files for record in tfrecord.TFRecordReader(f))
  return _read


def load_tfrecords(path: str, schema: Optional[Schema] = None,
                   binary_features: Optional[Set[str]] = None,
                   num_partitions: Optional[int] = None,
                   lazy: bool = False):
  """Load TFRecord file(s) into (partitions, schema).

  ``path`` may be a file, a directory of part files, or a glob. The schema
  is inferred from the first record when not given (parity:
  reference loadTFRecords + infer_schema, dfutil.py:44-81).

  With ``lazy=True`` the driver reads at most ONE record (for schema
  inference): each returned partition is a zero-arg callable producing the
  rows of one part file, resolved executor-side by ``cluster.train`` /
  ``cluster.inference`` feeders and by ``save_as_tfrecords(engine=...)``
  — the executor-side parse path of the reference's loadTFRecords, whose
  records were decoded by Spark tasks, never the driver.
  """
  files = _list_tfrecord_files(path)

  inferred = schema
  if lazy:
    if inferred is None:
      # scan files until the first record (a leading part file may be
      # empty); only that one record is ever decoded on the driver
      for f in files:
        for record in tfrecord.TFRecordReader(f):
          inferred = infer_schema(record, binary_features)
          logger.info("inferred schema: %s", inferred)
          break
        if inferred is not None:
          break
      if inferred is None:
        raise ValueError(
            "no records in %r to infer a schema from; pass schema=" % path)
    k = max(1, min(num_partitions, len(files))) if num_partitions \
        else len(files)
    groups = [files[i::k] for i in range(k)]
    partitions = [_lazy_file_reader(g, inferred) for g in groups if g]
    _loaded_paths[_path_key(path)] = inferred
    return partitions, inferred

  partitions: List[List[Tuple]] = []
  for f in files:
    rows = []
    for record in tfrecord.TFRecordReader(f):
      if inferred is None:
        inferred = infer_schema(record, binary_features)
        logger.info("inferred schema: %s", inferred)
      rows.append(from_example(record, inferred))
    partitions.append(rows)

  if inferred is None:
    raise ValueError(
        "no records found in %r to infer a schema from; pass schema= or a "
        "schema hint" % path)

  if num_partitions and num_partitions != len(partitions):
    flat = [r for p in partitions for r in p]
    k = max(1, num_partitions)
    partitions = [flat[i::k] for i in range(k)]

  _loaded_paths[_path_key(path)] = inferred
  return partitions, inferred
