"""Dependency-free protobuf wire codec for ``tf.train.Example``.

The reference converted rows ↔ tf.train.Example through the TensorFlow
proto classes (reference dfutil.py:84-131,171-212) and, on the JVM, through
org.tensorflow protos (DFUtil.scala:119-258). This module implements the
small fixed subset of the protobuf wire format those messages use, so
TFRecord/Example interop needs neither TensorFlow nor a JVM at runtime.

Message layout (tensorflow/core/example/{example,feature}.proto):
  Example        { Features features = 1; }
  Features       { map<string, Feature> feature = 1; }
  Feature        { oneof kind: BytesList=1, FloatList=2, Int64List=3 }
  BytesList      { repeated bytes value = 1; }
  FloatList      { repeated float value = 1 [packed=true]; }
  Int64List      { repeated int64 value = 1 [packed=true]; }

``decode_example`` accepts packed and unpacked repeated encodings (both are
legal on the wire); ``encode_example`` emits the canonical packed form.
"""

import struct
from typing import Dict, List, Tuple, Union

FeatureValue = Union[List[bytes], List[float], List[int]]


# --- varint / wire primitives ----------------------------------------------


def _write_varint(out: bytearray, value: int) -> None:
  while True:
    b = value & 0x7F
    value >>= 7
    if value:
      out.append(b | 0x80)
    else:
      out.append(b)
      return


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
  result = 0
  shift = 0
  while True:
    b = buf[pos]
    pos += 1
    result |= (b & 0x7F) << shift
    if not b & 0x80:
      return result, pos
    shift += 7
    if shift > 63:
      raise ValueError("varint too long")


def _write_tag(out: bytearray, field: int, wire_type: int) -> None:
  _write_varint(out, (field << 3) | wire_type)


def _write_len_delimited(out: bytearray, field: int, payload: bytes) -> None:
  _write_tag(out, field, 2)
  _write_varint(out, len(payload))
  out.extend(payload)


# --- encoding ---------------------------------------------------------------


def _encode_feature(values: FeatureValue) -> bytes:
  if not values:
    # empty feature: a BytesList message with zero entries
    out = bytearray()
    _write_len_delimited(out, 1, b"")
    return bytes(out)

  first = values[0]
  if isinstance(first, (bytes, bytearray, str)):
    blist = bytearray()
    for v in values:
      if isinstance(v, str):
        v = v.encode("utf-8")
      _write_len_delimited(blist, 1, bytes(v))
    kind_field = 1
    payload = bytes(blist)
  elif isinstance(first, float):
    packed = struct.pack("<%df" % len(values), *values)
    flist = bytearray()
    _write_len_delimited(flist, 1, packed)
    kind_field = 2
    payload = bytes(flist)
  elif isinstance(first, (int,)):
    packed = bytearray()
    for v in values:
      _write_varint(packed, v & 0xFFFFFFFFFFFFFFFF)
    ilist = bytearray()
    _write_len_delimited(ilist, 1, bytes(packed))
    kind_field = 3
    payload = bytes(ilist)
  else:
    raise TypeError("unsupported feature value type: %r" % type(first))

  out = bytearray()
  _write_len_delimited(out, kind_field, payload)
  return bytes(out)


def encode_example(features: Dict[str, FeatureValue]) -> bytes:
  """Serialize {name: list-of-values} to a tf.train.Example proto."""
  features_msg = bytearray()
  for name in sorted(features):
    entry = bytearray()
    _write_len_delimited(entry, 1, name.encode("utf-8"))
    _write_len_delimited(entry, 2, _encode_feature(features[name]))
    _write_len_delimited(features_msg, 1, bytes(entry))
  example = bytearray()
  _write_len_delimited(example, 1, bytes(features_msg))
  return bytes(example)


# --- decoding ---------------------------------------------------------------


def _iter_fields(buf: bytes):
  pos = 0
  n = len(buf)
  while pos < n:
    tag, pos = _read_varint(buf, pos)
    field, wire_type = tag >> 3, tag & 7
    if wire_type == 0:
      value, pos = _read_varint(buf, pos)
    elif wire_type == 2:
      length, pos = _read_varint(buf, pos)
      value = buf[pos:pos + length]
      pos += length
    elif wire_type == 5:
      value = buf[pos:pos + 4]
      pos += 4
    elif wire_type == 1:
      value = buf[pos:pos + 8]
      pos += 8
    else:
      raise ValueError("unsupported wire type %d" % wire_type)
    yield field, wire_type, value


def _decode_feature(buf: bytes) -> FeatureValue:
  for field, wire_type, value in _iter_fields(buf):
    if field == 1:      # BytesList
      return [bytes(v) for f, _, v in _iter_fields(value) if f == 1]
    if field == 2:      # FloatList
      out: List[float] = []
      for f, wt, v in _iter_fields(value):
        if f != 1:
          continue
        if wt == 2:     # packed
          out.extend(struct.unpack("<%df" % (len(v) // 4), v))
        else:           # unpacked fixed32
          out.extend(struct.unpack("<f", v))
      return out
    if field == 3:      # Int64List
      ints: List[int] = []
      for f, wt, v in _iter_fields(value):
        if f != 1:
          continue
        if wt == 2:     # packed varints
          pos = 0
          while pos < len(v):
            raw, pos = _read_varint(v, pos)
            ints.append(raw - (1 << 64) if raw >= (1 << 63) else raw)
        else:
          ints.append(v - (1 << 64) if v >= (1 << 63) else v)
      return ints
  return []


def decode_example(data: bytes) -> Dict[str, FeatureValue]:
  """Parse a serialized tf.train.Example into {name: list-of-values}."""
  features: Dict[str, FeatureValue] = {}
  for field, _, value in _iter_fields(data):
    if field != 1:
      continue
    for f2, _, entry in _iter_fields(value):
      if f2 != 1:
        continue
      name = None
      feat: FeatureValue = []
      for f3, _, v3 in _iter_fields(entry):
        if f3 == 1:
          name = v3.decode("utf-8")
        elif f3 == 2:
          feat = _decode_feature(v3)
      if name is not None:
        features[name] = feat
  return features
