"""Data interop: TFRecord codec, tf.Example wire codec, schema tools.

Replaces the reference's dfutil.py + the tensorflow-hadoop jar + the Scala
DFUtil/SimpleTypeParser layer (SURVEY.md §2.2) with a JVM-free stack:
a native C++ record codec (masked CRC32C framing), a dependency-free
protobuf wire codec for ``tf.train.Example``, schema inference with
binary/type hints, and a ``struct<name:type,...>`` hint-string parser.
"""

from tensorflowonspark_tpu.data.tfrecord import (  # noqa: F401
    TFRecordReader, TFRecordWriter, native_available,
)
from tensorflowonspark_tpu.data.example_codec import (  # noqa: F401
    encode_example, decode_example,
)
from tensorflowonspark_tpu.data.schema import parse_schema  # noqa: F401
from tensorflowonspark_tpu.data.indexed import (  # noqa: F401
    CheckpointableInput, IndexedTFRecordDataset, checkpointable_input,
)
