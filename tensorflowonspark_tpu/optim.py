"""Optimizer construction: schedules + clipping as first-class config.

The reference delegated all optimization to TF user code (its examples
hand-build Keras optimizers, e.g. reference examples/mnist/keras/
mnist_spark.py); a standalone training framework should offer the
standard LLM recipe — AdamW with linear warmup into cosine (or linear)
decay and global-norm gradient clipping — as one call. Returns plain
optax transforms, so anything accepting an optax ``GradientTransformation``
(``transformer.create_state(tx=...)``, flax TrainState) composes.
"""

from typing import Optional

SCHEDULES = ("constant", "cosine", "linear")
OPTIMIZERS = ("adamw", "lion", "adafactor", "sgd")


def make_schedule(learning_rate: float, schedule: str = "constant",
                  warmup_steps: int = 0, decay_steps: int = 0,
                  end_value: float = 0.0):
  """An optax schedule: optional linear warmup from 0, then the decay.

  ``decay_steps`` counts AFTER warmup; required for cosine/linear.
  """
  import optax

  if schedule not in SCHEDULES:
    raise ValueError("schedule must be one of %s, got %r"
                     % (SCHEDULES, schedule))
  if schedule == "constant":
    base = optax.constant_schedule(learning_rate)
  else:
    if decay_steps <= 0:
      raise ValueError("decay_steps must be > 0 for %r" % (schedule,))
    if schedule == "cosine":
      base = optax.cosine_decay_schedule(learning_rate, decay_steps,
                                         alpha=end_value / learning_rate
                                         if learning_rate else 0.0)
    else:
      base = optax.linear_schedule(learning_rate, end_value, decay_steps)
  if warmup_steps > 0:
    warmup = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    return optax.join_schedules([warmup, base], [warmup_steps])
  return base


def default_decay_mask(params):
  """True for params that should receive weight decay: matrices and
  larger (kernels, embedding tables); False for vectors/scalars
  (LayerNorm scales/offsets, biases) — the standard LLM recipe.
  """
  import jax

  return jax.tree_util.tree_map(lambda p: getattr(p, "ndim", 0) >= 2,
                                params)


def _lr_scaled_weight_decay(sched, weight_decay: float, mask):
  """Decoupled (AdamW-style) weight decay: ``updates -= lr_t · wd · p``.

  For cores whose optax implementation lacks an lr-scaled decay term:
  ``optax.adafactor`` applies its ``weight_decay_rate`` RAW per step
  (un-scaled by the schedule — 0.01 there means shrinking params 1% every
  step, warmup included), and ``optax.sgd`` has no decay at all. This
  transform gives both the same ``lr * weight_decay`` semantics adamw and
  lion use, honoring the decay mask.
  """
  import jax
  import jax.numpy as jnp
  import optax

  def init_fn(params):
    del params
    return optax.ScaleByScheduleState(count=jnp.zeros([], jnp.int32))

  def update_fn(updates, state, params=None):
    if params is None:
      raise ValueError("weight decay requires params")
    lr = sched(state.count)
    m = mask(params) if callable(mask) else mask
    if m is None:
      new = jax.tree.map(lambda u, p: u - lr * weight_decay * p,
                         updates, params)
    else:
      new = jax.tree.map(
          lambda u, p, mm: u - lr * weight_decay * p if mm else u,
          updates, params, m)
    return new, optax.ScaleByScheduleState(count=state.count + 1)

  return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(learning_rate: float = 3e-4,
                   weight_decay: float = 0.01,
                   schedule: str = "constant",
                   warmup_steps: int = 0,
                   decay_steps: int = 0,
                   end_value: float = 0.0,
                   clip_norm: float = 0.0,
                   b1: float = 0.9, b2: float = 0.95,
                   decay_mask="auto",
                   optimizer: str = "adamw",
                   momentum: float = 0.9,
                   grad_accum_steps: int = 1,
                   tx_extra: Optional[object] = None):
  """The standard training recipe around a chosen optimizer core.

  ``optimizer`` selects the core update rule:

  - ``"adamw"`` (default) — the standard LLM recipe.
  - ``"lion"`` — sign-momentum update; half Adam's optimizer memory (one
    moment, not two). Typical recipes use a ~3-10x smaller learning rate
    and larger weight decay than AdamW.
  - ``"adafactor"`` — factored second moments: O(rows+cols) optimizer
    memory per matrix instead of O(rows·cols), the classic TPU
    memory-saver for very large embeddings/models.
  - ``"sgd"`` — Nesterov momentum SGD (``momentum``), the ResNet recipe.

  ``clip_norm`` > 0 prepends global-norm gradient clipping; ``tx_extra``
  (an optax transform) is chained last, e.g. ``optax.ema`` or a custom
  accumulator. ``decay_mask`` controls which params get weight decay:
  ``"auto"`` (default) decays only ndim>=2 params (kernels/embeddings,
  not norms/biases), ``None`` decays everything, or pass an explicit
  optax-style mask (pytree of bools or callable). ``b1``/``b2`` apply to
  adamw/lion; ``momentum`` to sgd.

  ``grad_accum_steps`` > 1 wraps the whole chain in ``optax.MultiSteps``:
  gradients average over k consecutive ``update`` calls and the model
  moves once per k — train an effective batch k× the per-step batch at
  the per-step batch's memory (the non-pipeline microbatching; schedules
  advance once per EFFECTIVE step, as they should).
  """
  import optax

  if optimizer not in OPTIMIZERS:
    raise ValueError("optimizer must be one of %s, got %r"
                     % (OPTIMIZERS, optimizer))
  sched = make_schedule(learning_rate, schedule, warmup_steps, decay_steps,
                        end_value)
  if isinstance(decay_mask, str) and decay_mask == "auto":
    decay_mask = default_decay_mask if weight_decay else None
  parts = []
  if clip_norm and clip_norm > 0:
    parts.append(optax.clip_by_global_norm(clip_norm))
  if optimizer == "adamw":
    core = optax.adamw(sched, b1=b1, b2=b2,
                       weight_decay=weight_decay, mask=decay_mask)
  elif optimizer == "lion":
    core = optax.lion(sched, b1=b1, b2=b2,
                      weight_decay=weight_decay, mask=decay_mask)
  elif optimizer == "adafactor":
    # decay added via _lr_scaled_weight_decay: optax.adafactor's own
    # weight_decay_rate is applied raw per step, NOT scaled by the lr
    # schedule — the shared weight_decay default would destroy training
    core = optax.adafactor(learning_rate=sched)
  else:   # sgd (optax.sgd has no decay term of its own)
    core = optax.sgd(sched, momentum=momentum, nesterov=True)
  parts.append(core)
  if optimizer in ("adafactor", "sgd") and weight_decay:
    parts.append(_lr_scaled_weight_decay(sched, weight_decay, decay_mask))
  if tx_extra is not None:
    parts.append(tx_extra)
  tx = optax.chain(*parts) if len(parts) > 1 else parts[0]
  if grad_accum_steps > 1:
    tx = optax.MultiSteps(tx, every_k_schedule=grad_accum_steps)
  return tx
