"""Pipeline parallelism: microbatched stage execution over a mesh axis.

A capability the reference lacked (SURVEY.md §2.3: "Pipeline parallelism:
No"), here implemented TPU-natively: stages live on consecutive devices of
the ``pipeline`` mesh axis, activations advance between neighbors with
``lax.ppermute`` (ICI neighbor exchange), and microbatches are interleaved
down the pipe in a static schedule — fully jittable.

Two schedules:

- :func:`pipeline_apply` — GPipe fill-drain forward, differentiable
  through JAX AD (the backward pipelines in reverse through the ppermute
  transpose). Simple, composes with ``jax.grad``; activation storage grows
  with the number of microbatches.
- :func:`pipeline_train_step` — 1F1B: ONE loop interleaving each stage's
  forwards with backward steps of earlier microbatches, grads produced by
  per-stage ``jax.vjp`` with rematerialized stage forwards. Peak
  *intermediate-activation* storage is a ring buffer of ``2 * n_stages``
  microbatch inputs per device, independent of microbatch count — the
  memory property the 1F1B schedule exists for. Model INPUT/target
  microbatches are SCATTERED along the pipeline axis too (each device
  starts with ``n_micro / n_stages`` of them) and ride a one-hop-per-step
  ppermute conveyor to the stage that consumes them — tokens toward
  stage 0 (which also stashes each block in a ``2S``-slot ring for its
  backward embed-vjp), targets toward the last stage. Per-device input
  memory is O(batch / n_stages + n_stages) instead of O(batch); when
  ``n_micro % n_stages != 0`` the inputs fall back to replication
  (round-4 verdict item 6).

Constraints: every stage maps activations of one shape to the same shape
(true for stacked Transformer blocks), and stage parameters are stacked on
a leading stage axis sharded ``P('pipeline')``.
"""

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import mesh as mesh_lib
from tensorflowonspark_tpu.utils import compat


def _split_microbatches(arr, num_microbatches: int, mesh):
  """Reshape [batch, ...] → [n_micro, micro_batch, ...], checking both the
  microbatch split and that each microbatch divides over the data axes
  (otherwise shard_map fails with an opaque spec error)."""
  b = arr.shape[0]
  assert b % num_microbatches == 0, \
      "batch %d not divisible into %d microbatches" % (b, num_microbatches)
  micro_b = b // num_microbatches
  data_size = mesh_lib.axis_size(mesh, *mesh_lib.data_axes(mesh))
  assert micro_b % data_size == 0, \
      "microbatch size %d (batch %d / %d microbatches) not divisible by " \
      "the data-axis extent %d" % (micro_b, b, num_microbatches, data_size)
  return arr.reshape((num_microbatches, micro_b) + arr.shape[1:])


def _pipeline_local(stage_params, x_micro, stage_fn: Callable,
                    axis_name: str):
  """shard_map body. stage_params: this device's stage (leading axis
  squeezed); x_micro: [n_micro, micro_batch, ...] (replicated along the
  pipeline axis)."""
  n_stages = compat.jax_axis_size(axis_name)
  idx = lax.axis_index(axis_name)
  n_micro = x_micro.shape[0]
  total_steps = n_micro + n_stages - 1

  act0 = jnp.zeros_like(x_micro[0])
  out0 = jnp.zeros_like(x_micro)

  def body(t, carry):
    received, outputs = carry
    # stage 0 injects microbatch t (clamped; junk beyond n_micro never
    # reaches the output buffer)
    fresh = lax.dynamic_index_in_dim(
        x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
    inp = jnp.where(idx == 0, fresh, received)
    y = stage_fn(stage_params, inp)
    # the last stage finishes microbatch (t - n_stages + 1) at step t
    out_slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
    should_store = jnp.logical_and(idx == n_stages - 1,
                                   t >= n_stages - 1)
    current = lax.dynamic_index_in_dim(outputs, out_slot, 0,
                                       keepdims=False)
    outputs = lax.dynamic_update_index_in_dim(
        outputs, jnp.where(should_store, y, current), out_slot, 0)
    # advance activations one stage down the ring
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    received = lax.ppermute(y, axis_name, perm)
    return received, outputs

  _, outputs = lax.fori_loop(0, total_steps, body, (act0, out0))
  # broadcast the last stage's outputs to every pipeline rank
  mask = (idx == n_stages - 1).astype(outputs.dtype)
  return lax.psum(outputs * mask, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, x, mesh,
                   num_microbatches: int,
                   axis_name: str = mesh_lib.AXIS_PIPELINE):
  """Apply ``num_stages`` stages to ``x`` with microbatched pipelining.

  Args:
    stage_fn: ``(params_for_one_stage, activation) -> activation`` with
      matching input/output shapes.
    stage_params: pytree stacked on a leading stage axis of size
      ``mesh.shape[axis_name]`` (shard it ``P(axis_name)``).
    x: [batch, ...] global activations (batch divisible by
      ``num_microbatches``).
    mesh: device mesh containing ``axis_name``.

  Returns [batch, ...] outputs.
  """
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map

  x_micro = _split_microbatches(x, num_microbatches, mesh)

  param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
  fn = functools.partial(_pipeline_local, stage_fn=stage_fn,
                         axis_name=axis_name)
  # squeeze the stage axis inside: each device sees stage_params[0]
  def _local(params, xm):
    squeezed = jax.tree.map(lambda p: p[0], params)
    return fn(squeezed, xm)

  # shard the per-microbatch batch dim over the data axes so each data
  # slice pipelines only its batch shard (replicating would duplicate the
  # whole computation across the data axis)
  batch_axes = mesh_lib.data_axes(mesh)
  x_spec = P(None, batch_axes or None)
  out = shard_map(_local, mesh=mesh, in_specs=(param_specs, x_spec),
                  out_specs=x_spec, check_vma=False)(stage_params, x_micro)
  return out.reshape(x.shape)


def pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                        stage_params, x, targets, mesh,
                        num_microbatches: int,
                        axis_name: str = mesh_lib.AXIS_PIPELINE):
  """1F1B pipelined loss + gradients in one pass.

  Unlike ``jax.grad`` over :func:`pipeline_apply` (whole-loop AD storing
  every iteration's activations), the 1F1B schedule interleaves forward
  and backward in a single loop and keeps only a ``2 * n_stages``-slot
  stage-input ring per device — constant in the number of microbatches —
  with one rematerialized stage forward per backward step (the standard
  1F1B / remat trade). Input/target microbatches are scattered along the
  pipeline axis too when ``num_microbatches`` divides by the stage count
  (per-device input memory ``O(n_micro/S + S)`` blocks plus one
  token+target ppermute hop per step — see the module docstring);
  indivisible counts fall back to replication.

  Args:
    stage_fn: ``(params_for_one_stage, activation) -> activation`` with
      matching input/output shapes.
    loss_fn: ``(final_activation_micro, target_micro) -> scalar`` (mean
      over the microbatch), differentiable in its first argument.
    stage_params: pytree stacked on a leading stage axis of size
      ``mesh.shape[axis_name]``.
    x: [batch, ...] inputs; ``targets``: [batch, ...] per-example targets.
    num_microbatches: must divide batch.

  Returns ``(loss, grads)`` — loss is the mean over the global batch;
  grads match ``stage_params``' stacked layout.
  """
  # the degenerate full-model pipe: identity embed, no outer params, the
  # head is just the loss — ONE implementation of the schedule invariants
  loss, _, grads = pipeline_lm_train_step(
      lambda _outer, xx: xx, stage_fn,
      lambda _outer, y, tgt: loss_fn(y, tgt),
      {}, stage_params, x, targets, mesh, num_microbatches,
      axis_name=axis_name)
  return loss, grads


def _1f1b_lm_local(outer_params, stage_params, tok_arr, tgt_arr,
                   embed_fn: Callable, stage_fn: Callable,
                   head_loss_fn: Callable, axis_name: str,
                   other_axes: tuple, scattered: bool):
  """shard_map body: the 1F1B schedule for one device (= one stage), with
  embed on stage 0, the block stack pipelined, head+loss on the last stage.

  The schedule — per global step ``t`` every stage runs, in lockstep:

  - a FORWARD of microbatch ``m_f = t - s`` (masked outside
    ``[0, n_micro)``), storing its input in a ring buffer of ``2S`` slots.
    Stage 0's forward slot first embeds the entering microbatch's tokens
    (``lax.cond`` keeps the embed off other stages — under shard_map the
    predicate is a per-device scalar, not a batched one, so it compiles to
    a real HLO ``conditional``, not a select; asserted by
    ``test_parallel.py::TestPipeline1F1B::test_cond_is_real_branch``);
  - a BACKWARD of microbatch ``m_b = t - (2S - 1) + s``: the stage input
    is read back from the ring, the stage forward is rematerialized under
    ``jax.vjp``, and the incoming cotangent is the next stage's grad from
    the previous step. The last stage's backward slot runs head+loss under
    ``jax.vjp`` w.r.t. ``outer_params``, seeding the cotangent chain;
    stage 0's backward slot pushes its input cotangent through the embed's
    vjp, accumulating the embed side of ``outer_params``' grads. With tied
    embeddings the table's two contributions live on different stages and
    are summed by the closing psum over the pipeline axis.

  Ring-slot lifetime analysis: input of ``m`` is written at ``t = m + s``
  and read at ``t = m + 2S - 1 - s``, a gap of at most ``2S - 1`` steps,
  so 2S slots never collide. Activations flow ``s -> s+1`` and cotangents
  ``s -> s-1`` by ppermute, one hop per step; total steps
  ``n_micro + 2S - 1``. Grads accumulate in f32 (summing n_micro
  pre-scaled contributions in bf16 would swamp the small addends) and are
  cast back to the param dtype at the end.

  Input scattering (``scattered=True``, requires ``n_micro % S == 0``):
  instead of every device holding all ``n_micro`` token/target
  microbatches, each starts with ``L = n_micro / S`` of them and two
  ppermute conveyors rotate whole local buffers one hop per step —

  - TOKENS rotate toward stage 0 from a round-robin start (microbatch
    ``m`` home stage ``m % S``): after ``t`` one-hop rotations stage 0
    holds home-stage-``t % S``'s buffer, whose local index ``t // S`` is
    exactly microbatch ``t = m_f`` — just in time for the embed. Stage 0
    stashes each consumed block in a ``2S``-slot token ring (same
    lifetime argument as the activation ring: written at ``t = m``, read
    by the embed-vjp at ``t = m + 2S - 1``);
  - TARGETS rotate toward the LAST stage from home stage
    ``(-(m+1)) % S``: at ``t`` stage ``S-1`` holds home-stage
    ``(S-1-t) % S``'s buffer and reads local index ``t//S - 1`` —
    microbatch ``t - S = m_b`` of its backward slot, just in time for
    head+loss.

  Per-device input memory drops from ``2 n_micro`` blocks to
  ``2L + 2S``; the price is one token + one target block on the ICI per
  step, a few percent of the activation ppermute's bytes at transformer
  widths.
  """
  S = compat.jax_axis_size(axis_name)
  s = lax.axis_index(axis_name)
  if scattered:
    tok_local, tgt_local = tok_arr[0], tgt_arr[0]   # [L, micro_b, ...]
    L = tok_local.shape[0]
    n_micro = L * S
  else:
    tok_local, tgt_local = tok_arr, tgt_arr         # [n_micro, micro_b, ...]
    n_micro = tok_local.shape[0]
  ring = 2 * S
  total_steps = n_micro + 2 * S - 1
  inv_micro = jnp.float32(1.0 / n_micro)

  fwd_perm = [(i, (i + 1) % S) for i in range(S)]
  bwd_perm = [(i, (i - 1) % S) for i in range(S)]

  params = jax.tree.map(lambda p: p[0], stage_params)
  act_sd = jax.eval_shape(embed_fn, outer_params, tok_local[0])
  act0 = jnp.zeros(act_sd.shape, act_sd.dtype)
  ring0 = jnp.zeros((ring,) + act0.shape, act0.dtype)
  g_stage0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
  g_outer0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                          outer_params)

  def body(t, carry):
    if scattered:
      (fwd_recv, bwd_recv, ring_buf, g_stage, g_outer, loss_acc,
       tok_buf, tgt_buf, tok_ring) = carry
    else:
      fwd_recv, bwd_recv, ring_buf, g_stage, g_outer, loss_acc = carry
      tok_buf, tgt_buf, tok_ring = tok_local, tgt_local, None

    # ---- forward slot ----
    m_f = t - s
    f_valid = jnp.logical_and(m_f >= 0, m_f < n_micro)
    mf_c = jnp.clip(m_f, 0, n_micro - 1)
    if scattered:
      # only stage 0 consumes tokens; its conveyor position at step t is
      # local index t // S of the buffer that arrived (junk elsewhere,
      # masked by the s == 0 cond below)
      tok_f = lax.dynamic_index_in_dim(
          tok_buf, jnp.clip(t // S, 0, L - 1), 0, keepdims=False)
      tslot = mf_c % ring
      cur_t = lax.dynamic_index_in_dim(tok_ring, tslot, 0, keepdims=False)
      tok_ring = lax.dynamic_update_index_in_dim(
          tok_ring, jnp.where(f_valid, tok_f, cur_t), tslot, 0)
    else:
      tok_f = lax.dynamic_index_in_dim(tok_buf, mf_c, 0, keepdims=False)
    inj = lax.cond(s == 0,
                   lambda tok: embed_fn(outer_params, tok).astype(act0.dtype),
                   lambda tok: act0, tok_f)
    inp = jnp.where(s == 0, inj, fwd_recv)
    slot_f = mf_c % ring
    cur = lax.dynamic_index_in_dim(ring_buf, slot_f, 0, keepdims=False)
    ring_buf = lax.dynamic_update_index_in_dim(
        ring_buf, jnp.where(f_valid, inp, cur), slot_f, 0)
    y = stage_fn(params, inp)

    # ---- backward slot ----
    m_b = t - (2 * S - 1) + s
    b_valid = jnp.logical_and(m_b >= 0, m_b < n_micro)
    mb_c = jnp.clip(m_b, 0, n_micro - 1)
    saved = lax.dynamic_index_in_dim(ring_buf, mb_c % ring, 0,
                                     keepdims=False)
    y_b, vjp_fn = jax.vjp(stage_fn, params, saved)
    if scattered:
      # the head stage's conveyor delivers its backward target just in
      # time (junk on other stages, masked by the s == S-1 cond below)
      tgt = lax.dynamic_index_in_dim(
          tgt_buf, jnp.clip(t // S - 1, 0, L - 1), 0, keepdims=False)
    else:
      tgt = lax.dynamic_index_in_dim(tgt_buf, mb_c, 0, keepdims=False)

    def _head(operand):
      yb, tg = operand
      lval, head_vjp = jax.vjp(
          lambda op, yy: head_loss_fn(op, yy, tg), outer_params, yb)
      g_o, g_y = head_vjp(inv_micro.astype(lval.dtype))
      return (lval.astype(jnp.float32), g_o, g_y.astype(yb.dtype))

    def _no_head(operand):
      yb, tg = operand
      return (jnp.zeros((), jnp.float32),
              jax.tree.map(jnp.zeros_like, outer_params),
              jnp.zeros_like(yb))

    lval, g_outer_h, g_seed = lax.cond(s == S - 1, _head, _no_head,
                                       (y_b, tgt))
    g_in = jnp.where(s == S - 1, g_seed, bwd_recv)
    g_par, g_x = vjp_fn(g_in)

    if scattered:
      # stage 0 re-reads the tokens it stashed at forward time
      tok_b = lax.dynamic_index_in_dim(tok_ring, mb_c % ring, 0,
                                       keepdims=False)
    else:
      tok_b = lax.dynamic_index_in_dim(tok_buf, mb_c, 0, keepdims=False)

    def _embed_bwd(operand):
      gx, tok = operand
      _, embed_vjp = jax.vjp(lambda op: embed_fn(op, tok), outer_params)
      return embed_vjp(gx)[0]

    def _no_embed_bwd(operand):
      return jax.tree.map(jnp.zeros_like, outer_params)

    g_outer_e = lax.cond(s == 0, _embed_bwd, _no_embed_bwd, (g_x, tok_b))

    g_stage = jax.tree.map(
        lambda a, g: a + jnp.where(b_valid, g, jnp.zeros_like(g)).astype(
            jnp.float32),
        g_stage, g_par)
    g_outer = jax.tree.map(
        lambda a, gh, ge: a + jnp.where(
            b_valid, (gh.astype(jnp.float32) + ge.astype(jnp.float32)),
            0.0),
        g_outer, g_outer_h, g_outer_e)
    loss_acc = loss_acc + jnp.where(b_valid, lval, 0.0)

    fwd_recv = lax.ppermute(y, axis_name, fwd_perm)
    bwd_recv = lax.ppermute(g_x, axis_name, bwd_perm)
    if scattered:
      # conveyors advance one hop: tokens toward stage 0, targets toward
      # the head stage
      tok_buf = lax.ppermute(tok_buf, axis_name, bwd_perm)
      tgt_buf = lax.ppermute(tgt_buf, axis_name, fwd_perm)
      return (fwd_recv, bwd_recv, ring_buf, g_stage, g_outer, loss_acc,
              tok_buf, tgt_buf, tok_ring)
    return fwd_recv, bwd_recv, ring_buf, g_stage, g_outer, loss_acc

  carry0 = (act0, act0, ring0, g_stage0, g_outer0,
            jnp.zeros((), jnp.float32))
  if scattered:
    tok_ring0 = jnp.zeros((ring,) + tok_local.shape[1:], tok_local.dtype)
    carry0 = carry0 + (tok_local, tgt_local, tok_ring0)
  out_carry = lax.fori_loop(0, total_steps, body, carry0)
  g_stage, g_outer, loss_acc = out_carry[3], out_carry[4], out_carry[5]

  loss = lax.psum(loss_acc, axis_name) * inv_micro
  # outer grads live on stages 0 and S-1 only; psum joins them (and, for a
  # tied table, sums its embed- and head-side contributions)
  g_outer = jax.tree.map(lambda g: lax.psum(g, axis_name), g_outer)
  if other_axes:
    loss = lax.pmean(loss, other_axes)
    g_stage = jax.tree.map(lambda g: lax.pmean(g, other_axes), g_stage)
    g_outer = jax.tree.map(lambda g: lax.pmean(g, other_axes), g_outer)
  g_stage = jax.tree.map(lambda g, p: g.astype(p.dtype)[None], g_stage,
                         params)
  g_outer = jax.tree.map(lambda g, p: g.astype(p.dtype), g_outer,
                         outer_params)
  return loss, g_outer, g_stage


def pipeline_lm_train_step(embed_fn: Callable, stage_fn: Callable,
                           head_loss_fn: Callable, outer_params,
                           stage_params, tokens, targets, mesh,
                           num_microbatches: int,
                           axis_name: str = mesh_lib.AXIS_PIPELINE):
  """Full-model 1F1B training step: embed → pipelined stages → head/loss.

  Args:
    embed_fn: ``(outer_params, tokens_micro) -> activation`` — runs on the
      first stage only.
    stage_fn: ``(stage_params_one, activation) -> activation`` — the
      pipelined body (e.g. a chunk of Transformer blocks).
    head_loss_fn: ``(outer_params, activation, targets_micro) -> scalar``
      mean loss over the microbatch — runs on the last stage only. May
      share params with ``embed_fn`` (tied embeddings): each param's grad
      is the sum of both contributions.
    outer_params: everything outside the pipelined stages (embedding
      table, final norm, head) — replicated along the pipeline axis.
    stage_params: pytree stacked on a leading stage axis.
    tokens/targets: [batch, ...] int inputs and targets.

  Returns ``(loss, outer_grads, stage_grads)``.
  """
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map

  tok_micro = _split_microbatches(tokens, num_microbatches, mesh)
  tgt_micro = _split_microbatches(targets, num_microbatches, mesh)

  stage_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
  outer_specs = jax.tree.map(lambda _: P(), outer_params)
  batch_axes = mesh_lib.data_axes(mesh)
  other_axes = tuple(a for a in mesh.axis_names if a != axis_name)
  S = mesh.shape[axis_name]
  scattered = S > 1 and num_microbatches % S == 0
  if scattered:
    # scatter inputs along the pipeline axis for the conveyors
    # (_1f1b_lm_local docstring): tokens round-robin (microbatch m home
    # stage m % S), targets at home stage (-(m+1)) % S — the stage-flip
    # of the same round-robin layout
    L = num_microbatches // S
    tok_arr = tok_micro.reshape((L, S) + tok_micro.shape[1:]).swapaxes(0, 1)
    tgt_arr = tgt_micro.reshape(
        (L, S) + tgt_micro.shape[1:]).swapaxes(0, 1)[::-1]
    x_spec = P(axis_name, None, batch_axes or None)
  else:
    tok_arr, tgt_arr = tok_micro, tgt_micro
    x_spec = P(None, batch_axes or None)
  fn = functools.partial(_1f1b_lm_local, embed_fn=embed_fn,
                         stage_fn=stage_fn, head_loss_fn=head_loss_fn,
                         axis_name=axis_name, other_axes=other_axes,
                         scattered=scattered)
  return shard_map(
      fn, mesh=mesh,
      in_specs=(outer_specs, stage_specs, x_spec, x_spec),
      out_specs=(P(), outer_specs, stage_specs), check_vma=False)(
          outer_params, stage_params, tok_arr, tgt_arr)
