"""Pipeline parallelism: GPipe-style microbatched stage execution.

A capability the reference lacked (SURVEY.md §2.3: "Pipeline parallelism:
No"), here implemented TPU-natively: stages live on consecutive devices of
the ``pipeline`` mesh axis, activations advance between neighbors with
``lax.ppermute`` (ICI neighbor exchange), and microbatches are interleaved
down the pipe in a static ``lax.fori_loop`` schedule — fully jittable and
differentiable (the backward pass pipelines in reverse automatically
through the ppermute transpose).

Constraints: every stage maps activations of one shape to the same shape
(true for stacked Transformer blocks), and stage parameters are stacked on
a leading stage axis sharded ``P('pipeline')``.
"""

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import mesh as mesh_lib


def _pipeline_local(stage_params, x_micro, stage_fn: Callable,
                    axis_name: str):
  """shard_map body. stage_params: this device's stage (leading axis
  squeezed); x_micro: [n_micro, micro_batch, ...] (replicated along the
  pipeline axis)."""
  n_stages = lax.axis_size(axis_name)
  idx = lax.axis_index(axis_name)
  n_micro = x_micro.shape[0]
  total_steps = n_micro + n_stages - 1

  act0 = jnp.zeros_like(x_micro[0])
  out0 = jnp.zeros_like(x_micro)

  def body(t, carry):
    received, outputs = carry
    # stage 0 injects microbatch t (clamped; junk beyond n_micro never
    # reaches the output buffer)
    fresh = lax.dynamic_index_in_dim(
        x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
    inp = jnp.where(idx == 0, fresh, received)
    y = stage_fn(stage_params, inp)
    # the last stage finishes microbatch (t - n_stages + 1) at step t
    out_slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
    should_store = jnp.logical_and(idx == n_stages - 1,
                                   t >= n_stages - 1)
    current = lax.dynamic_index_in_dim(outputs, out_slot, 0,
                                       keepdims=False)
    outputs = lax.dynamic_update_index_in_dim(
        outputs, jnp.where(should_store, y, current), out_slot, 0)
    # advance activations one stage down the ring
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    received = lax.ppermute(y, axis_name, perm)
    return received, outputs

  _, outputs = lax.fori_loop(0, total_steps, body, (act0, out0))
  # broadcast the last stage's outputs to every pipeline rank
  mask = (idx == n_stages - 1).astype(outputs.dtype)
  return lax.psum(outputs * mask, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, x, mesh,
                   num_microbatches: int,
                   axis_name: str = mesh_lib.AXIS_PIPELINE):
  """Apply ``num_stages`` stages to ``x`` with microbatched pipelining.

  Args:
    stage_fn: ``(params_for_one_stage, activation) -> activation`` with
      matching input/output shapes.
    stage_params: pytree stacked on a leading stage axis of size
      ``mesh.shape[axis_name]`` (shard it ``P(axis_name)``).
    x: [batch, ...] global activations (batch divisible by
      ``num_microbatches``).
    mesh: device mesh containing ``axis_name``.

  Returns [batch, ...] outputs.
  """
  from jax import shard_map

  n_stages = mesh.shape[axis_name]
  b = x.shape[0]
  assert b % num_microbatches == 0, \
      "batch %d not divisible into %d microbatches" % (b, num_microbatches)
  x_micro = x.reshape((num_microbatches, b // num_microbatches) +
                      x.shape[1:])

  param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
  fn = functools.partial(_pipeline_local, stage_fn=stage_fn,
                         axis_name=axis_name)
  # squeeze the stage axis inside: each device sees stage_params[0]
  def _local(params, xm):
    squeezed = jax.tree.map(lambda p: p[0], params)
    return fn(squeezed, xm)

  # shard the per-microbatch batch dim over the data axes so each data
  # slice pipelines only its batch shard (replicating would duplicate the
  # whole computation across the data axis)
  batch_axes = mesh_lib.data_axes(mesh)
  x_spec = P(None, batch_axes or None)
  out = shard_map(_local, mesh=mesh, in_specs=(param_specs, x_spec),
                  out_specs=x_spec, check_vma=False)(stage_params, x_micro)
  return out.reshape((b,) + x.shape[1:])
