"""Pipeline parallelism: microbatched stage execution over a mesh axis.

A capability the reference lacked (SURVEY.md §2.3: "Pipeline parallelism:
No"), here implemented TPU-natively: stages live on consecutive devices of
the ``pipeline`` mesh axis, activations advance between neighbors with
``lax.ppermute`` (ICI neighbor exchange), and microbatches are interleaved
down the pipe in a static schedule — fully jittable.

Two schedules:

- :func:`pipeline_apply` — GPipe fill-drain forward, differentiable
  through JAX AD (the backward pipelines in reverse through the ppermute
  transpose). Simple, composes with ``jax.grad``; activation storage grows
  with the number of microbatches.
- :func:`pipeline_train_step` — 1F1B: ONE loop interleaving each stage's
  forwards with backward steps of earlier microbatches, grads produced by
  per-stage ``jax.vjp`` with rematerialized stage forwards. Peak
  *intermediate-activation* storage is a ring buffer of ``2 * n_stages``
  microbatch inputs per device, independent of microbatch count — the
  memory property the 1F1B schedule exists for. (The model INPUT/target
  microbatches themselves are replicated along the pipeline axis, like
  in :func:`pipeline_apply`; for deep stacks it is the loop residuals,
  not the inputs, that dominate.)

Constraints: every stage maps activations of one shape to the same shape
(true for stacked Transformer blocks), and stage parameters are stacked on
a leading stage axis sharded ``P('pipeline')``.
"""

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import mesh as mesh_lib


def _split_microbatches(arr, num_microbatches: int, mesh):
  """Reshape [batch, ...] → [n_micro, micro_batch, ...], checking both the
  microbatch split and that each microbatch divides over the data axes
  (otherwise shard_map fails with an opaque spec error)."""
  b = arr.shape[0]
  assert b % num_microbatches == 0, \
      "batch %d not divisible into %d microbatches" % (b, num_microbatches)
  micro_b = b // num_microbatches
  data_size = mesh_lib.axis_size(mesh, *mesh_lib.data_axes(mesh))
  assert micro_b % data_size == 0, \
      "microbatch size %d (batch %d / %d microbatches) not divisible by " \
      "the data-axis extent %d" % (micro_b, b, num_microbatches, data_size)
  return arr.reshape((num_microbatches, micro_b) + arr.shape[1:])


def _pipeline_local(stage_params, x_micro, stage_fn: Callable,
                    axis_name: str):
  """shard_map body. stage_params: this device's stage (leading axis
  squeezed); x_micro: [n_micro, micro_batch, ...] (replicated along the
  pipeline axis)."""
  n_stages = lax.axis_size(axis_name)
  idx = lax.axis_index(axis_name)
  n_micro = x_micro.shape[0]
  total_steps = n_micro + n_stages - 1

  act0 = jnp.zeros_like(x_micro[0])
  out0 = jnp.zeros_like(x_micro)

  def body(t, carry):
    received, outputs = carry
    # stage 0 injects microbatch t (clamped; junk beyond n_micro never
    # reaches the output buffer)
    fresh = lax.dynamic_index_in_dim(
        x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
    inp = jnp.where(idx == 0, fresh, received)
    y = stage_fn(stage_params, inp)
    # the last stage finishes microbatch (t - n_stages + 1) at step t
    out_slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
    should_store = jnp.logical_and(idx == n_stages - 1,
                                   t >= n_stages - 1)
    current = lax.dynamic_index_in_dim(outputs, out_slot, 0,
                                       keepdims=False)
    outputs = lax.dynamic_update_index_in_dim(
        outputs, jnp.where(should_store, y, current), out_slot, 0)
    # advance activations one stage down the ring
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    received = lax.ppermute(y, axis_name, perm)
    return received, outputs

  _, outputs = lax.fori_loop(0, total_steps, body, (act0, out0))
  # broadcast the last stage's outputs to every pipeline rank
  mask = (idx == n_stages - 1).astype(outputs.dtype)
  return lax.psum(outputs * mask, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, x, mesh,
                   num_microbatches: int,
                   axis_name: str = mesh_lib.AXIS_PIPELINE):
  """Apply ``num_stages`` stages to ``x`` with microbatched pipelining.

  Args:
    stage_fn: ``(params_for_one_stage, activation) -> activation`` with
      matching input/output shapes.
    stage_params: pytree stacked on a leading stage axis of size
      ``mesh.shape[axis_name]`` (shard it ``P(axis_name)``).
    x: [batch, ...] global activations (batch divisible by
      ``num_microbatches``).
    mesh: device mesh containing ``axis_name``.

  Returns [batch, ...] outputs.
  """
  from jax import shard_map

  x_micro = _split_microbatches(x, num_microbatches, mesh)

  param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
  fn = functools.partial(_pipeline_local, stage_fn=stage_fn,
                         axis_name=axis_name)
  # squeeze the stage axis inside: each device sees stage_params[0]
  def _local(params, xm):
    squeezed = jax.tree.map(lambda p: p[0], params)
    return fn(squeezed, xm)

  # shard the per-microbatch batch dim over the data axes so each data
  # slice pipelines only its batch shard (replicating would duplicate the
  # whole computation across the data axis)
  batch_axes = mesh_lib.data_axes(mesh)
  x_spec = P(None, batch_axes or None)
  out = shard_map(_local, mesh=mesh, in_specs=(param_specs, x_spec),
                  out_specs=x_spec, check_vma=False)(stage_params, x_micro)
  return out.reshape(x.shape)


def _1f1b_local(stage_params, x_micro, t_micro, stage_fn: Callable,
                loss_fn: Callable, axis_name: str, other_axes: tuple):
  """shard_map body: the 1F1B schedule for one device (= one stage).

  Per global step ``t`` every stage runs, in lockstep:

  - a FORWARD of microbatch ``m_f = t - s`` (masked outside
    ``[0, n_micro)``), storing its input in a ring buffer of ``2S`` slots;
  - a BACKWARD of microbatch ``m_b = t - (2S - 1) + s``: the stage input
    is read back from the ring, the stage forward is rematerialized under
    ``jax.vjp``, and the incoming cotangent is the next stage's grad from
    the previous step (the last stage seeds from the loss). Ring-slot
    lifetime analysis: input of ``m`` is written at ``t = m + s`` and read
    at ``t = m + 2S - 1 - s``, a gap of at most ``2S - 1`` steps, so 2S
    slots never collide.

  Activations flow ``s -> s+1`` and cotangents ``s -> s-1`` by ppermute,
  one hop per step; total steps ``n_micro + 2S - 1``.
  """
  S = lax.axis_size(axis_name)
  s = lax.axis_index(axis_name)
  n_micro = x_micro.shape[0]
  ring = 2 * S
  total_steps = n_micro + 2 * S - 1
  inv_micro = jnp.float32(1.0 / n_micro)

  fwd_perm = [(i, (i + 1) % S) for i in range(S)]
  bwd_perm = [(i, (i - 1) % S) for i in range(S)]

  params = jax.tree.map(lambda p: p[0], stage_params)  # squeeze stage axis
  act0 = jnp.zeros_like(x_micro[0])
  ring0 = jnp.zeros((ring,) + x_micro.shape[1:], x_micro.dtype)
  # accumulate grads in f32 (like loss_acc): summing n_micro pre-scaled
  # contributions in bf16 would swamp the small addends
  grads0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)

  def body(t, carry):
    fwd_recv, bwd_recv, ring_buf, grads, loss_acc = carry

    # ---- forward slot: microbatch t - s enters this stage ----
    m_f = t - s
    f_valid = jnp.logical_and(m_f >= 0, m_f < n_micro)
    mf_c = jnp.clip(m_f, 0, n_micro - 1)
    inj = lax.dynamic_index_in_dim(x_micro, mf_c, 0, keepdims=False)
    inp = jnp.where(s == 0, inj, fwd_recv)
    slot_f = mf_c % ring
    cur = lax.dynamic_index_in_dim(ring_buf, slot_f, 0, keepdims=False)
    ring_buf = lax.dynamic_update_index_in_dim(
        ring_buf, jnp.where(f_valid, inp, cur), slot_f, 0)
    y = stage_fn(params, inp)

    # ---- backward slot: microbatch t - (2S-1) + s leaves this stage ----
    m_b = t - (2 * S - 1) + s
    b_valid = jnp.logical_and(m_b >= 0, m_b < n_micro)
    mb_c = jnp.clip(m_b, 0, n_micro - 1)
    saved = lax.dynamic_index_in_dim(ring_buf, mb_c % ring, 0,
                                     keepdims=False)
    y_b, vjp_fn = jax.vjp(stage_fn, params, saved)
    tgt = lax.dynamic_index_in_dim(t_micro, mb_c, 0, keepdims=False)
    lval, loss_vjp = jax.vjp(loss_fn, y_b, tgt)
    # cotangent dtype must match the loss primal's (bf16 losses included)
    g_loss = loss_vjp(inv_micro.astype(lval.dtype))[0]
    g_in = jnp.where(s == S - 1, g_loss.astype(y_b.dtype), bwd_recv)
    g_par, g_x = vjp_fn(g_in)
    grads = jax.tree.map(
        lambda a, g: a + jnp.where(b_valid, g, jnp.zeros_like(g)).astype(
            jnp.float32),
        grads, g_par)
    loss_acc = loss_acc + jnp.where(
        jnp.logical_and(b_valid, s == S - 1), lval.astype(jnp.float32), 0.0)

    fwd_recv = lax.ppermute(y, axis_name, fwd_perm)
    bwd_recv = lax.ppermute(g_x, axis_name, bwd_perm)
    return fwd_recv, bwd_recv, ring_buf, grads, loss_acc

  _, _, _, grads, loss_acc = lax.fori_loop(
      0, total_steps, body, (act0, act0, ring0, grads0,
                             jnp.zeros((), jnp.float32)))

  # only the last stage accumulated loss; share it down the pipe, and
  # average loss/grads over the data (and any other non-pipeline) axes
  loss = lax.psum(loss_acc, axis_name) * inv_micro
  if other_axes:
    loss = lax.pmean(loss, other_axes)
    grads = jax.tree.map(lambda g: lax.pmean(g, other_axes), grads)
  # back to the param dtype, re-growing the leading stage axis so
  # out_spec P(axis_name) stacks stages
  grads = jax.tree.map(lambda g, p: g.astype(p.dtype)[None], grads, params)
  return loss, grads


def pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                        stage_params, x, targets, mesh,
                        num_microbatches: int,
                        axis_name: str = mesh_lib.AXIS_PIPELINE):
  """1F1B pipelined loss + gradients in one pass.

  Unlike ``jax.grad`` over :func:`pipeline_apply` (whole-loop AD storing
  every iteration's activations), the 1F1B schedule interleaves forward
  and backward in a single loop and keeps only a ``2 * n_stages``-slot
  stage-input ring per device — constant in the number of microbatches —
  with one rematerialized stage forward per backward step (the standard
  1F1B / remat trade). Input/target microbatches are still replicated
  down the pipe; the saving is in loop residuals.

  Args:
    stage_fn: ``(params_for_one_stage, activation) -> activation`` with
      matching input/output shapes.
    loss_fn: ``(final_activation_micro, target_micro) -> scalar`` (mean
      over the microbatch), differentiable in its first argument.
    stage_params: pytree stacked on a leading stage axis of size
      ``mesh.shape[axis_name]``.
    x: [batch, ...] inputs; ``targets``: [batch, ...] per-example targets.
    num_microbatches: must divide batch.

  Returns ``(loss, grads)`` — loss is the mean over the global batch;
  grads match ``stage_params``' stacked layout.
  """
  from jax import shard_map

  x_micro = _split_microbatches(x, num_microbatches, mesh)
  t_micro = _split_microbatches(targets, num_microbatches, mesh)

  param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
  batch_axes = mesh_lib.data_axes(mesh)
  x_spec = P(None, batch_axes or None)
  other_axes = tuple(a for a in mesh.axis_names if a != axis_name)
  fn = functools.partial(_1f1b_local, stage_fn=stage_fn, loss_fn=loss_fn,
                         axis_name=axis_name, other_axes=other_axes)
  loss, grads = shard_map(
      fn, mesh=mesh, in_specs=(param_specs, x_spec, x_spec),
      out_specs=(P(), param_specs), check_vma=False)(
          stage_params, x_micro, t_micro)
  return loss, grads
