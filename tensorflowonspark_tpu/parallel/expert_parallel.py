"""Expert parallelism: a mixture-of-experts FFN sharded over the
``expert`` mesh axis.

Beyond-parity capability (SURVEY.md §2.3: "Expert parallelism: No"): each
device of the ``expert`` axis holds a disjoint slice of the expert stack;
tokens are dispatched with one-hot combine weights (Shazeer-style einsum
dispatch) and partial expert outputs are combined with a single ``psum``
over the expert axis. Top-1 or top-k routing (renormalized combine
weights) with a Switch/GShard :func:`load_balancing_loss`; gating runs
replicated (it is a tiny
matmul), expert FFNs run sharded.

Two dispatch strategies:

- :func:`moe_ffn` — dense masked dispatch: every token visits every expert
  shard (masked), combined with one psum. Exact and simple.
- :func:`moe_ffn_a2a` — GShard-style all-to-all token exchange with
  capacity bounds: each device runs only its experts on only their
  assigned tokens (the communication-optimal variant).
"""

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import mesh as mesh_lib


def init_moe_params(rng, num_experts: int, d_model: int, d_ff: int,
                    dtype=jnp.float32) -> Dict[str, jax.Array]:
  kg, k1, k2 = jax.random.split(rng, 3)
  scale_in = 1.0 / (d_model ** 0.5)
  return {
      "w_gate": jax.random.normal(kg, (d_model, num_experts), dtype) * scale_in,
      "w_up": jax.random.normal(k1, (num_experts, d_model, d_ff), dtype)
              * scale_in,
      "w_down": jax.random.normal(k2, (num_experts, d_ff, d_model), dtype)
                * (1.0 / (d_ff ** 0.5)),
  }


def _router_probs(x, w_gate):
  """Router forward: softmax probabilities [T, E] — the single source of
  the gating math for every dispatch strategy and the aux loss."""
  logits = x.astype(jnp.float32) @ w_gate.astype(jnp.float32)
  return jax.nn.softmax(logits, axis=-1)


def _topk_dispatch(probs, top_k: int):
  """Binary multi-hot dispatch [T, E] selecting each token's top-k experts."""
  _, idx = lax.top_k(probs, top_k)
  return jax.nn.one_hot(idx, probs.shape[-1],
                        dtype=probs.dtype).sum(axis=1)


def _combine_weights(probs, dispatch, top_k: int):
  """Combine weights [T, E] for a multi-hot dispatch: gate probabilities,
  renormalized over the selected set for top_k > 1. The single source of
  this math for every dispatch strategy."""
  selected = probs * dispatch
  if top_k == 1:
    return selected
  return selected / jnp.sum(selected, axis=-1, keepdims=True)


def route(params, x, top_k: int = 1):
  """Top-k routing: (dispatch [T,E] multi-hot, combine [T,E], probs [T,E]).

  Dispatch selects which experts process each token (binary — experts see
  the raw token); combine weights each selected expert's output by its
  gate probability (renormalized over the selected set for top_k > 1).
  Returns the router probabilities too so callers can derive the
  load-balancing loss without a second router forward.
  """
  probs = _router_probs(x, params["w_gate"])
  dispatch = _topk_dispatch(probs, top_k)               # [T, E]
  return dispatch, _combine_weights(probs, dispatch, top_k), probs


def _route(params, x, top_k: int = 1):
  return route(params, x, top_k)[:2]


def load_balancing_loss(params, x, top_k: int = 1):
  """Auxiliary load-balancing loss (Switch/GShard style).

  ``E · Σ_e fraction_of_tokens_routed_to_e · mean_router_prob_e`` — equals
  1.0 under perfectly uniform routing; add a small multiple to the task
  loss to keep experts utilized.
  """
  probs = _router_probs(x, params["w_gate"])
  dispatch = _topk_dispatch(probs, top_k)
  return aux_loss_from(probs, dispatch, top_k)


def aux_loss_from(probs, dispatch, top_k: int = 1):
  """Load-balancing loss from an existing routing (no router recompute)."""
  fraction = jnp.mean(dispatch, axis=0) / top_k         # [E]
  mean_prob = jnp.mean(probs, axis=0)                   # [E]
  return probs.shape[-1] * jnp.sum(fraction * mean_prob)


def moe_ffn_reference(params, x, top_k: int = 1, routing=None):
  """Single-device reference: x [T, D] -> [T, D]. ``routing`` optionally
  supplies a precomputed (dispatch, combine) pair from :func:`route`."""
  dispatch, combine = routing if routing is not None \
      else _route(params, x, top_k)                    # [T, E] each
  xf = x.astype(jnp.float32)
  h = jax.nn.relu(jnp.einsum("te,td,edf->etf", dispatch, xf,
                             params["w_up"].astype(jnp.float32)))
  out = jnp.einsum("etf,efd->etd", h,
                   params["w_down"].astype(jnp.float32))
  return jnp.einsum("etd,te->td", out, combine).astype(x.dtype)


def _moe_local(x, dispatch, combine, w_up, w_down):
  """shard_map body: local expert slice. x [T,D] replicated over expert;
  dispatch/combine [T,E_local]; w_up [E_local,D,F]; w_down [E_local,F,D]."""
  xf = x.astype(jnp.float32)
  h = jax.nn.relu(jnp.einsum("te,td,edf->etf", dispatch, xf,
                             w_up.astype(jnp.float32)))
  out = jnp.einsum("etf,efd->etd", h, w_down.astype(jnp.float32))
  partial = jnp.einsum("etd,te->td", out, combine)
  return lax.psum(partial, mesh_lib.AXIS_EXPERT).astype(x.dtype)


def moe_ffn(params, x, mesh, top_k: int = 1, routing=None):
  """Expert-sharded MoE FFN. x: [tokens, d_model] (shard tokens over the
  data axes as usual); expert weights sharded over the expert axis."""
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map

  dispatch, combine = routing if routing is not None \
      else _route(params, x, top_k)                    # [T, E] replicated
  batch_axes = mesh_lib.data_axes(mesh) or None
  fn = shard_map(
      _moe_local, mesh=mesh,
      in_specs=(P(batch_axes), P(batch_axes, mesh_lib.AXIS_EXPERT),
                P(batch_axes, mesh_lib.AXIS_EXPERT),
                P(mesh_lib.AXIS_EXPERT), P(mesh_lib.AXIS_EXPERT)),
      out_specs=P(batch_axes), check_vma=False)
  return fn(x, dispatch, combine, params["w_up"], params["w_down"])


def _moe_a2a_local(x, w_gate, w_up, w_down, capacity: int, top_k: int):
  """shard_map body for all-to-all dispatch (GShard-style).

  x: [T_local, D] (tokens sharded over data×expert axes);
  w_gate replicated [D, E]; w_up/w_down sharded [E_local, ...].
  Tokens route to their top-k global experts, dispatch tensors are
  exchanged over the ``expert`` axis with two all-to-alls, and each device
  runs only its own experts on only their assigned tokens
  (capacity-bounded; overflow (token, expert) assignments are dropped, the
  standard GShard capacity semantics).
  """
  xf = x.astype(jnp.float32)
  probs = _router_probs(x, w_gate)                  # [T, E]
  mh = _topk_dispatch(probs, top_k)                 # [T, E] binary multi-hot
  combine_w = _combine_weights(probs, mh, top_k)
  # position of each (token, expert) assignment in that expert's queue
  pos = (jnp.cumsum(mh, axis=0) - 1.0) * mh                      # [T, E]
  keep = mh * (pos < capacity)
  dispatch = keep[:, :, None] * jax.nn.one_hot(
      pos.astype(jnp.int32), capacity, dtype=jnp.float32)        # [T, E, C]
  combine = dispatch * combine_w[:, :, None]                     # [T, E, C]

  expert_in = jnp.einsum("tec,td->ecd", dispatch, xf)   # [E, C, D]
  # exchange: every device sends each peer its slice of the expert dim
  expert_in = lax.all_to_all(expert_in, mesh_lib.AXIS_EXPERT,
                             split_axis=0, concat_axis=1, tiled=True)
  h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", expert_in,
                             w_up.astype(jnp.float32)))
  out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(jnp.float32))
  out = lax.all_to_all(out, mesh_lib.AXIS_EXPERT,
                       split_axis=1, concat_axis=0, tiled=True)
  y = jnp.einsum("ecd,tec->td", out, combine)
  return y.astype(x.dtype)


def moe_ffn_a2a(params, x, mesh, capacity_factor: float = 2.0,
                top_k: int = 1):
  """Expert-parallel MoE with all-to-all token dispatch.

  Communication-optimal variant of :func:`moe_ffn`: tokens are sharded
  over the data AND expert axes, each device dispatches its tokens to the
  owning experts with two ``all_to_all`` collectives (ICI neighbor
  traffic), and only capacity-bounded expert work runs per device —
  instead of every device touching every token. Top-k routing with
  capacity ``ceil(T_local · k / E) * capacity_factor`` per expert per
  shard; overflow assignments contribute zero output (standard GShard
  semantics; with top-k > 1 a token's surviving experts keep their
  renormalized weights).
  """
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map

  num_experts = params["w_gate"].shape[-1]
  batch_axes = mesh_lib.data_axes(mesh)
  token_axes = tuple(batch_axes) + (mesh_lib.AXIS_EXPERT,)
  shards = mesh_lib.axis_size(mesh, *token_axes)
  t_local = x.shape[0] // shards
  capacity = max(1, int(-(-t_local * top_k // num_experts) * capacity_factor))

  fn = functools.partial(_moe_a2a_local, capacity=capacity, top_k=top_k)
  return shard_map(
      fn, mesh=mesh,
      in_specs=(P(token_axes), P(), P(mesh_lib.AXIS_EXPERT),
                P(mesh_lib.AXIS_EXPERT)),
      out_specs=P(token_axes), check_vma=False)(
          x, params["w_gate"], params["w_up"], params["w_down"])


def shard_moe_params(params, mesh):
  """Place MoE params: gate replicated, expert stacks sharded."""
  from jax.sharding import NamedSharding
  put = lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec))  # noqa: E731
  return {
      "w_gate": put(params["w_gate"], P()),
      "w_up": put(params["w_up"], P(mesh_lib.AXIS_EXPERT)),
      "w_down": put(params["w_down"], P(mesh_lib.AXIS_EXPERT)),
  }
