"""Expert parallelism: a mixture-of-experts FFN sharded over the
``expert`` mesh axis.

Beyond-parity capability (SURVEY.md §2.3: "Expert parallelism: No"): each
device of the ``expert`` axis holds a disjoint slice of the expert stack;
tokens are dispatched with one-hot combine weights (Shazeer-style einsum
dispatch) and partial expert outputs are combined with a single ``psum``
over the expert axis. Top-1 routing; gating runs replicated (it is a tiny
matmul), expert FFNs run sharded.

The dense dispatch keeps every token on every expert shard (masked), which
is exact and simple; an all-to-all token exchange is the future
communication-optimal variant.
"""

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import mesh as mesh_lib


def init_moe_params(rng, num_experts: int, d_model: int, d_ff: int,
                    dtype=jnp.float32) -> Dict[str, jax.Array]:
  kg, k1, k2 = jax.random.split(rng, 3)
  scale_in = 1.0 / (d_model ** 0.5)
  return {
      "w_gate": jax.random.normal(kg, (d_model, num_experts), dtype) * scale_in,
      "w_up": jax.random.normal(k1, (num_experts, d_model, d_ff), dtype)
              * scale_in,
      "w_down": jax.random.normal(k2, (num_experts, d_ff, d_model), dtype)
                * (1.0 / (d_ff ** 0.5)),
  }


def _route(params, x):
  """Top-1 routing: [T, E] combine weights (gate prob on the argmax)."""
  logits = x.astype(jnp.float32) @ params["w_gate"].astype(jnp.float32)
  probs = jax.nn.softmax(logits, axis=-1)
  top = jnp.argmax(probs, axis=-1)
  onehot = jax.nn.one_hot(top, probs.shape[-1], dtype=probs.dtype)
  return onehot * jnp.max(probs, axis=-1, keepdims=True)


def moe_ffn_reference(params, x):
  """Single-device reference: x [T, D] -> [T, D]."""
  combine = _route(params, x)                          # [T, E]
  xf = x.astype(jnp.float32)
  h = jax.nn.relu(jnp.einsum("te,td,edf->etf", combine, xf,
                             params["w_up"].astype(jnp.float32)))
  out = jnp.einsum("etf,efd->etd", h,
                   params["w_down"].astype(jnp.float32))
  return jnp.einsum("etd,te->td", out, combine).astype(x.dtype)


def _moe_local(x, combine, w_up, w_down):
  """shard_map body: local expert slice. x [T,D] replicated over expert;
  combine [T,E_local]; w_up [E_local,D,F]; w_down [E_local,F,D]."""
  xf = x.astype(jnp.float32)
  h = jax.nn.relu(jnp.einsum("te,td,edf->etf", combine, xf,
                             w_up.astype(jnp.float32)))
  out = jnp.einsum("etf,efd->etd", h, w_down.astype(jnp.float32))
  partial = jnp.einsum("etd,te->td", out, combine)
  return lax.psum(partial, mesh_lib.AXIS_EXPERT).astype(x.dtype)


def moe_ffn(params, x, mesh):
  """Expert-sharded MoE FFN. x: [tokens, d_model] (shard tokens over the
  data axes as usual); expert weights sharded over the expert axis."""
  from jax import shard_map

  combine = _route(params, x)                          # [T, E] replicated
  batch_axes = mesh_lib.data_axes(mesh) or None
  fn = shard_map(
      _moe_local, mesh=mesh,
      in_specs=(P(batch_axes), P(batch_axes, mesh_lib.AXIS_EXPERT),
                P(mesh_lib.AXIS_EXPERT), P(mesh_lib.AXIS_EXPERT)),
      out_specs=P(batch_axes), check_vma=False)
  return fn(x, combine, params["w_up"], params["w_down"])


def shard_moe_params(params, mesh):
  """Place MoE params: gate replicated, expert stacks sharded."""
  from jax.sharding import NamedSharding
  put = lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec))  # noqa: E731
  return {
      "w_gate": put(params["w_gate"], P()),
      "w_up": put(params["w_up"], P(mesh_lib.AXIS_EXPERT)),
      "w_down": put(params["w_down"], P(mesh_lib.AXIS_EXPERT)),
  }
