"""shard_map-level collective helpers.

The reference's training collectives were TF gRPC ring all-reduce inside
MultiWorkerMirroredStrategy plus an optional ``grpc+verbs`` RDMA path
(reference TFNode.py:129-131; SURVEY.md §2.4). The TPU equivalents are XLA
collectives over ICI/DCN; these helpers wrap the ``jax.lax`` primitives for
use inside ``shard_map`` sections, keeping axis names consistent with
``parallel.mesh``.
"""

from typing import Callable
import jax
import jax.numpy as jnp
from jax import lax


def psum_mean(x, axis_name: str):
  """All-reduce average over a mesh axis (gradient sync primitive)."""
  return lax.pmean(x, axis_name)


def all_reduce(x, axis_name: str):
  return lax.psum(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
  return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
  return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ring_permute(x, axis_name: str, shift: int = 1):
  """Rotate shards around the mesh-axis ring (neighbor exchange on ICI)."""
  n = lax.axis_size(axis_name)
  perm = [(i, (i + shift) % n) for i in range(n)]
  return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
  """Ulysses-style head/sequence exchange."""
  return lax.all_to_all(x, axis_name, split_axis=split_axis,
                        concat_axis=concat_axis, tiled=True)


def all_processes_agree(flag: bool) -> bool:
  """True iff ``flag`` is True in EVERY process of the jax.distributed
  group (host-level collective, safe outside jit).

  This is the primitive behind principled step agreement for uneven data
  partitions: synchronous SPMD collectives deadlock if any participant
  stops early, so all participants agree on "everyone still has data"
  before each step. (The reference instead trained a blind 90% of expected
  steps — examples/mnist/keras/mnist_spark.py:58-64.)
  """
  import jax
  import jax.numpy as jnp
  if jax.process_count() <= 1:
    return bool(flag)
  from jax.experimental import multihost_utils
  votes = multihost_utils.process_allgather(
      jnp.asarray([1 if flag else 0], jnp.int32))
  return bool(votes.min() == 1)


def shard_map_fn(fn: Callable, mesh, in_specs, out_specs,
                 check_vma: bool = False):
  """Thin wrapper over jax.shard_map bound to a mesh."""
  from jax import shard_map
  return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma)
