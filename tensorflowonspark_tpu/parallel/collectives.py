"""shard_map-level collective helpers.

The reference's training collectives were TF gRPC ring all-reduce inside
MultiWorkerMirroredStrategy plus an optional ``grpc+verbs`` RDMA path
(reference TFNode.py:129-131; SURVEY.md §2.4). The TPU equivalents are XLA
collectives over ICI/DCN; these helpers wrap the ``jax.lax`` primitives for
use inside ``shard_map`` sections, keeping axis names consistent with
``parallel.mesh``.
"""

from typing import Callable
import jax
import jax.numpy as jnp
from jax import lax

from tensorflowonspark_tpu.utils import compat


def psum_mean(x, axis_name: str):
  """All-reduce average over a mesh axis (gradient sync primitive)."""
  return lax.pmean(x, axis_name)


def all_reduce(x, axis_name: str):
  return lax.psum(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
  return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
  return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ring_permute(x, axis_name: str, shift: int = 1):
  """Rotate shards around the mesh-axis ring (neighbor exchange on ICI)."""
  n = compat.jax_axis_size(axis_name)
  perm = [(i, (i + shift) % n) for i in range(n)]
  return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
  """Ulysses-style head/sequence exchange."""
  return lax.all_to_all(x, axis_name, split_axis=split_axis,
                        concat_axis=concat_axis, tiled=True)


def hierarchical_all_reduce(x, ici_axis: str, dcn_axis: str,
                            scatter_axis: int = 0, mean: bool = False):
  """Bandwidth-optimal all-reduce across a two-tier ICI×DCN mesh.

  The naive ``psum`` over both axes sends the full tensor across the slow
  DCN tier once per device. This composes the standard hierarchy instead:
  reduce-scatter inside the pod (fast ICI), all-reduce only the 1/N shard
  across pods (DCN moves 1/N of the bytes), then all-gather back over ICI.
  Mathematically identical to ``psum(x, (ici_axis, dcn_axis))``; XLA emits
  the tiered collectives. Use inside shard_map for cross-pod gradient sync
  (the role the reference delegated to gRPC ring all-reduce inside
  MultiWorkerMirroredStrategy — SURVEY.md §2.4).

  The per-shard size of dimension ``scatter_axis`` must be divisible by
  the ICI axis size (psum_scatter's tiling requirement).
  """
  shard = lax.psum_scatter(x, ici_axis, scatter_dimension=scatter_axis,
                           tiled=True)
  shard = lax.psum(shard, dcn_axis)
  out = lax.all_gather(shard, ici_axis, axis=scatter_axis, tiled=True)
  if mean:
    out = out / (compat.jax_axis_size(ici_axis) *
                 compat.jax_axis_size(dcn_axis))
  return out


def sync_gradients(grads, axis_names, mean: bool = True):
  """All-reduce a gradient pytree over one or more mesh axes.

  For hand-written shard_map training steps (make_train_step's jit path
  gets this from GSPMD automatically): averages every leaf across the
  data-parallel axes in one fused pass.
  """
  if isinstance(axis_names, str):
    axis_names = (axis_names,)
  op = lax.pmean if mean else lax.psum
  return jax.tree.map(lambda g: op(g, axis_names), grads)


def broadcast_from(x, axis_name: str, src_index: int = 0):
  """Every shard receives shard ``src_index``'s value.

  Implemented as a masked psum (ppermute cannot express one-to-all: its
  source/destination pairs must form a permutation); XLA lowers this to a
  broadcast-shaped collective.
  """
  idx = lax.axis_index(axis_name)
  return lax.psum(jnp.where(idx == src_index, x, jnp.zeros_like(x)),
                  axis_name)


def global_norm(tree, axis_names=None):
  """L2 norm over a (possibly sharded) pytree of gradients.

  With ``axis_names``, per-shard partial squares are psum'd first so the
  result is the TRUE global norm of row-sharded leaves inside shard_map —
  the building block for gradient clipping that agrees across shards.
  """
  partial_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                   for g in jax.tree.leaves(tree))
  if axis_names:
    partial_sq = lax.psum(partial_sq, axis_names)
  return jnp.sqrt(partial_sq)


def clip_by_global_norm(tree, max_norm: float, axis_names=None):
  """Scale the pytree so its (cross-shard) global norm is <= max_norm."""
  norm = global_norm(tree, axis_names)
  scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
  return jax.tree.map(lambda g: (g * scale).astype(g.dtype), tree), norm


def all_processes_agree(flag: bool) -> bool:
  """True iff ``flag`` is True in EVERY process of the jax.distributed
  group (host-level collective, safe outside jit).

  This is the primitive behind principled step agreement for uneven data
  partitions: synchronous SPMD collectives deadlock if any participant
  stops early, so all participants agree on "everyone still has data"
  before each step. (The reference instead trained a blind 90% of expected
  steps — examples/mnist/keras/mnist_spark.py:58-64.)
  """
  import jax
  import jax.numpy as jnp
  if jax.process_count() <= 1:
    return bool(flag)
  from jax.experimental import multihost_utils
  votes = multihost_utils.process_allgather(
      jnp.asarray([1 if flag else 0], jnp.int32))
  return bool(votes.min() == 1)


def shard_map_fn(fn: Callable, mesh, in_specs, out_specs,
                 check_vma: bool = False):
  """Thin wrapper over jax.shard_map bound to a mesh."""
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map
  return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma)
