"""Independent-parallel runner: N single-node instances, no cluster.

Capability parity with the reference's ``TFParallel.run``
(/root/reference/tensorflowonspark/TFParallel.py:17-74): run a user fn once
per executor, optionally gang-scheduled under barrier execution with
placement info, with per-worker accelerator allocation — used for
embarrassingly-parallel batch inference
(reference examples/mnist/keras/mnist_inference.py:79).
"""

import logging
import os
from typing import List, Optional

from tensorflowonspark_tpu.engine.base import Engine
from tensorflowonspark_tpu.node import TPUNodeContext
from tensorflowonspark_tpu.utils import tpu_info

logger = logging.getLogger(__name__)


def run(engine: Engine, map_fn, tf_args=None,
        num_tasks: Optional[int] = None, use_barrier: bool = True,
        chips_per_node: int = 0, timeout: Optional[float] = None) -> List:
  """Run ``map_fn(tf_args, ctx)`` on ``num_tasks`` independent executors.

  With ``use_barrier`` the tasks are gang-scheduled and each ctx carries the
  addresses of all gang members (parity: BarrierTaskContext.getTaskInfos,
  TFParallel.py:43-56). Returns the per-task results.
  """
  n = num_tasks if num_tasks is not None else engine.num_executors

  def _task_body(task_id: int, addresses: List[str]):
    if chips_per_node and not os.environ.get("TOS_TPU_TEST_MODE"):
      topo = tpu_info.get_topology()
      if topo is not None:
        workers_per_host = max(1, topo.chips_per_host // chips_per_node)
        tpu_info.apply_chip_env(tpu_info.chip_env_for_worker(
            chips_per_node, task_id, workers_per_host))
    ctx = TPUNodeContext(
        executor_id=task_id, job_name="worker", task_index=task_id,
        cluster_spec={"worker": addresses},
        working_dir=os.getcwd())
    return map_fn(tf_args, ctx)

  if use_barrier:
    def _barrier_task(it, barrier_ctx):
      task_id = next(iter(it))
      return _task_body(task_id, barrier_ctx.get_task_infos())

    return engine.barrier_run(_barrier_task, num_tasks=n, timeout=timeout)

  def _plain_task(it):
    task_id = next(iter(it))
    return _task_body(task_id, [])

  return engine.run_on_executors(_plain_task, num_tasks=n).wait(
      timeout=timeout)
