"""TPU-native SPMD parallelism.

The reference delegated all distributed math to ``tf.distribute`` strategies
configured through the TF_CONFIG it synthesized (reference
TFSparkNode.py:376-384; strategy matrix in SURVEY.md §2.3). This package is
the TPU-first replacement: explicit device meshes + shardings compiled by
XLA/GSPMD to collectives over ICI/DCN.

- ``mesh``        — standard mesh axes (data/fsdp/tensor/sequence/pipeline/
                    expert), device factoring, multi-host awareness
- ``sharding``    — NamedSharding helpers + train-step factory (the analog of
                    MultiWorkerMirroredStrategy: sync data parallelism, plus
                    TP/FSDP the reference never had)
- ``collectives`` — shard_map-level collective helpers (psum/all_gather/
                    reduce_scatter/ring permute)
- ``ring_attention`` — sequence/context parallelism for long sequences
                    (blockwise online-softmax attention with KV blocks
                    rotating around the ICI ring)
- ``pipeline_parallel`` — GPipe-style microbatched stage parallelism
- ``runner``      — independent-parallel barrier runner (parity:
                    TFParallel.py)
- ``groups``      — elastic multi-group training: hierarchical data
                    parallelism (periodic cross-group weight sync over the
                    rendezvous plane) that survives group loss, resizes,
                    and reshards checkpoints across group counts
"""

from tensorflowonspark_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec, build_mesh, AXIS_DATA, AXIS_FSDP, AXIS_TENSOR, AXIS_SEQUENCE,
    AXIS_PIPELINE, AXIS_EXPERT,
)
