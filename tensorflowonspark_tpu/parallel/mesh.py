"""Device-mesh construction with the framework's standard axis names.

The capability here replaces the reference's cluster-spec-driven strategy
selection (SURVEY.md §2.3): instead of choosing a tf.distribute strategy, a
user picks a mesh shape over the named axes below and annotates shardings;
XLA inserts the collectives.

Axis conventions (orderered outer→inner so that the innermost axes map to
the fastest ICI loops):

- ``data``      batch sharding (pure DP; gradients all-reduced)
- ``fsdp``      batch + parameter sharding (ZeRO-style)
- ``pipeline``  layer-stage sharding
- ``expert``    MoE expert sharding
- ``sequence``  sequence/context sharding (ring attention)
- ``tensor``    within-layer parameter sharding (megatron-style TP)
"""

import logging
import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPELINE = "pipeline"
AXIS_EXPERT = "expert"
AXIS_SEQUENCE = "sequence"
AXIS_TENSOR = "tensor"

# outer→inner order; tensor innermost (highest-bandwidth neighbor exchanges)
CANONICAL_ORDER = (AXIS_DATA, AXIS_FSDP, AXIS_PIPELINE, AXIS_EXPERT,
                   AXIS_SEQUENCE, AXIS_TENSOR)


@dataclass
class MeshSpec:
  """Requested parallelism degrees; -1 on one axis means "absorb the rest"."""
  data: int = -1
  fsdp: int = 1
  pipeline: int = 1
  expert: int = 1
  sequence: int = 1
  tensor: int = 1

  def degrees(self) -> Dict[str, int]:
    return {AXIS_DATA: self.data, AXIS_FSDP: self.fsdp,
            AXIS_PIPELINE: self.pipeline, AXIS_EXPERT: self.expert,
            AXIS_SEQUENCE: self.sequence, AXIS_TENSOR: self.tensor}


def _topology_mesh_devices(devices, shape, names):
  """Topology-aware device assignment via ``jax.experimental.mesh_utils``.

  On TPU, device enumeration order does NOT track ICI adjacency — a plain
  ``reshape`` can land the innermost (tensor) axis on non-neighboring chips.
  ``create_device_mesh`` permutes devices using their physical ``coords`` so
  inner mesh axes ride the fastest ICI loops; on multi-slice topologies
  ``create_hybrid_device_mesh`` keeps exactly one axis (the outermost one
  whose degree the slice count divides — ``data`` first in canonical order)
  across the DCN boundary and everything else inside a slice.

  Returns the device ndarray, or None when not applicable (non-TPU devices,
  or no axis can absorb the slice count) — callers fall back to enumeration
  order, which is correct for CPU/virtual meshes.
  """
  if not devices or getattr(devices[0], "platform", "") != "tpu":
    return None
  from jax.experimental import mesh_utils

  try:
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if n_slices > 1:
      # only gradient-sync / stage-boundary axes tolerate DCN latency;
      # tensor/sequence/expert collectives are per-layer and must stay on ICI
      dcn_ok = (AXIS_DATA, AXIS_FSDP, AXIS_PIPELINE)
      dcn_shape, per_slice_shape = [], []
      carried = False
      for name, deg in zip(names, shape):
        if (not carried and name in dcn_ok and deg >= n_slices
            and deg % n_slices == 0):
          dcn_shape.append(n_slices)
          per_slice_shape.append(deg // n_slices)
          carried = True
        else:
          dcn_shape.append(1)
          per_slice_shape.append(deg)
      if not carried:
        logger.warning(
            "no mesh axis in %s can absorb %d slices; falling back to "
            "enumeration order (cross-slice collectives will ride DCN "
            "suboptimally)", dict(zip(names, shape)), n_slices)
        return None
      return mesh_utils.create_hybrid_device_mesh(
          per_slice_shape, dcn_shape, devices=devices)
    return mesh_utils.create_device_mesh(shape, devices=devices)
  except Exception as e:  # noqa: BLE001 - mesh_utils topology tables vary
    # by generation; an unrecognized topology must not break mesh bring-up
    logger.warning("topology-aware mesh construction failed (%s); "
                   "falling back to enumeration order", e)
    return None


def build_mesh(spec: Optional[MeshSpec] = None,
               devices: Optional[Sequence] = None,
               axis_names: Optional[Sequence[str]] = None):
  """Build a ``jax.sharding.Mesh`` over all (or given) devices.

  Exactly one axis may be -1; it absorbs whatever device count remains after
  the explicit axes divide in. Axes of degree 1 are kept in the mesh so
  sharding rules can always reference every canonical axis.

  On TPU the device layout is topology-aware (see
  :func:`_topology_mesh_devices`); elsewhere devices fill the mesh in
  enumeration order.
  """
  import jax
  from jax.sharding import Mesh

  spec = spec or MeshSpec()
  devices = list(devices if devices is not None else jax.devices())
  n = len(devices)

  degrees = spec.degrees()
  wildcard = [a for a, d in degrees.items() if d == -1]
  if len(wildcard) > 1:
    raise ValueError("at most one mesh axis may be -1, got %r" % wildcard)
  explicit = math.prod(d for d in degrees.values() if d != -1)
  if wildcard:
    if n % explicit != 0:
      raise ValueError(
          "explicit axes %r use %d-way parallelism which does not divide %d "
          "devices" % (degrees, explicit, n))
    degrees[wildcard[0]] = n // explicit
  elif explicit != n:
    raise ValueError("mesh %r needs %d devices, have %d"
                     % (degrees, explicit, n))

  names = tuple(axis_names or CANONICAL_ORDER)
  shape = tuple(degrees[a] for a in names)
  mesh_devices = _topology_mesh_devices(devices, shape, names)
  if mesh_devices is None:
    mesh_devices = np.asarray(devices).reshape(shape)
  mesh = Mesh(mesh_devices, names)
  logger.info("built mesh %s over %d device(s)",
              dict(zip(names, shape)), n)
  return mesh


def data_axes(mesh) -> tuple:
  """All axes a data batch is sharded over (data + fsdp)."""
  return tuple(a for a in (AXIS_DATA, AXIS_FSDP) if a in mesh.axis_names)


def axis_size(mesh, *axes: str) -> int:
  size = 1
  for a in axes:
    if a in mesh.axis_names:
      size *= mesh.shape[a]
  return size
