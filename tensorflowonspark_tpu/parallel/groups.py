"""Elastic multi-group training: hierarchical data parallelism that
survives group loss, resizes, and reshards its checkpoints.

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md) scales past one synchronous mesh with HIERARCHICAL data
parallelism: fast in-group collectives every step, periodic cross-group
weight synchronization on a slower plane. TF-Replicator's replica
abstraction explains why that shape is also the fault story: groups are
INTERCHANGEABLE — any group's post-sync state is the model — so losing
one shrinks the denominator instead of killing the job, and a rebooted
(or brand-new) group catches up by pulling the current weights and
rejoining at the next sync boundary. That is the elasticity the serving
plane already has (``serving.fleet`` ejects/readmits replicas) ported to
the training plane.

Three layers:

- :class:`SyncPlane` — driver-side round state, attached to the
  rendezvous :class:`~control.rendezvous.Server` as ``server.sync_plane``
  (the ``obs_sink`` pattern): serves the ``SYNC`` (contribute weights to
  a round), ``SYNCQ`` (poll for the merged result) and ``GROUP``
  (join/leave/lost/state) verbs. A round completes when every
  non-lost member contributed OR its deadline passes — the sync
  denominator shrinks to whoever showed up, so a dead group can delay a
  round by at most ``sync_timeout`` and can never stall training
  globally. Groups that miss ``miss_limit`` consecutive rounds are
  marked lost (the committed shrink); a lost group's next contribution
  is REJECTED so stale weights never poison the average — it must
  re-join (pulling current weights) instead.
- :class:`GroupSyncClient` — per-group client over
  :class:`~control.rendezvous.Client`; every wait is deadline-bounded
  (TOS001).
- :class:`GroupSet` — the in-process group runtime: N independent mesh
  groups (device subsets of this host, the same same-process topology
  the fleet's replicas use), each stepping the existing fused
  ``make_train_loop`` privately and syncing every ``sync_every`` steps.
  Chaos (``TOS_CHAOS_GROUP``) is consulted at each boundary;
  :meth:`GroupSet.save`/:meth:`GroupSet.restore_or` record/reshard the
  group topology through the checkpoint manifest.

Wire budget: a sync payload (one serialized weight pytree) must fit the
rendezvous frame cap (``rendezvous.MAX_MESSAGE_BYTES``, 4 MiB). That
bounds this plane to small/medium models or to syncing a parameter
subset; a chunked exchange can lift it later without changing the verbs.

Merge semantics: floating-point leaves are the weighted mean of the
round's contributions (weights = optimizer steps contributed, so uneven
rounds stay unbiased); non-float leaves (step counters, rng keys) take
the first contribution verbatim — averaging them is meaningless.
"""

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from tensorflowonspark_tpu.control import rendezvous
from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils import chaos

logger = logging.getLogger(__name__)

#: steps each group runs between cross-group syncs (GroupSet default)
ENV_GROUP_SYNC_EVERY = "TOS_GROUP_SYNC_EVERY"
#: seconds a round waits for stragglers after its first contribution
#: before merging with whoever showed up
ENV_GROUP_SYNC_TIMEOUT = "TOS_GROUP_SYNC_TIMEOUT"
#: consecutive missed rounds before a group is marked lost
ENV_GROUP_MISS_LIMIT = "TOS_GROUP_MISS_LIMIT"

_DEFAULT_SYNC_EVERY = 8
_DEFAULT_SYNC_TIMEOUT = 30.0
_DEFAULT_MISS_LIMIT = 2


def _env_int(name: str, default: int) -> int:
  try:
    return int(os.environ.get(name, default))
  except ValueError:
    return default


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


class GroupEvicted(RuntimeError):
  """The plane rejected this group's contribution because it was marked
  lost (missed too many rounds / supervisor committed the shrink). The
  group must re-join — pulling current weights — before syncing again."""


# -- payload codec ------------------------------------------------------------
#
# The wire carries a FLAT LEAF LIST (msgpack-safe: dtype string, shape
# list, raw bytes); the tree structure stays client-side — the server
# merges positionally and never needs jax. unpack_tree restores into the
# caller's template, which every member shares by construction.


def pack_tree(tree: Any) -> List[dict]:
  """Flatten a pytree of arrays into the wire leaf-list."""
  import jax
  import numpy as np
  out = []
  for leaf in jax.tree.leaves(tree):
    a = np.asarray(leaf)
    out.append({"dtype": str(a.dtype), "shape": list(a.shape),
                "data": a.tobytes()})
  return out


def unpack_tree(leaves: List[dict], template: Any) -> Any:
  """Rebuild a pytree with ``template``'s structure from the wire list."""
  import jax
  import numpy as np
  tmpl_leaves, treedef = jax.tree.flatten(template)
  if len(leaves) != len(tmpl_leaves):
    raise ValueError("payload has %d leaves, template has %d"
                     % (len(leaves), len(tmpl_leaves)))
  arrays = [np.frombuffer(rec["data"], dtype=rec["dtype"])
            .reshape(rec["shape"]).copy() for rec in leaves]
  return jax.tree.unflatten(treedef, arrays)


def merge_payloads(contribs: List[Tuple[float, List[dict]]]) -> List[dict]:
  """Weighted-mean merge of wire leaf-lists (float leaves; first-wins for
  the rest). Pure numpy — runs on the driver without jax."""
  import numpy as np
  if not contribs:
    raise ValueError("nothing to merge")
  weights = [max(0.0, float(w)) for w, _ in contribs]
  total = sum(weights) or float(len(contribs))
  first = contribs[0][1]
  merged = []
  for i, rec in enumerate(first):
    dtype = np.dtype(rec["dtype"])
    if dtype.kind != "f":
      merged.append(dict(rec))
      continue
    acc = np.zeros(rec["shape"], dtype=np.float64)
    for (w, leaves), wt in zip(contribs, weights):
      arr = np.frombuffer(leaves[i]["data"], dtype=leaves[i]["dtype"])
      acc += arr.reshape(rec["shape"]).astype(np.float64) * (wt or 1.0)
    acc /= (total or 1.0)
    merged.append({"dtype": rec["dtype"], "shape": list(rec["shape"]),
                   "data": acc.astype(dtype).tobytes()})
  return merged


# -- driver-side round state --------------------------------------------------


class SyncPlane(object):
  """Cross-group sync rounds + group membership, served over rendezvous.

  Attach to a :class:`control.rendezvous.Server` (``server.sync_plane =
  plane`` or :func:`attach_sync_plane`); the server delegates the
  SYNC/SYNCQ/GROUP verbs to :meth:`handle` and enriches HEALTH replies
  with :meth:`status` (→ ``obs_top``'s ``groups[...]`` line).

  All state transitions are driven by member requests and by round
  deadlines — there is no background thread, so the plane is exactly as
  alive as the server serving it.
  """

  def __init__(self, sync_timeout: Optional[float] = None,
               miss_limit: Optional[int] = None, keep_rounds: int = 4,
               time_fn=time.monotonic):
    self.sync_timeout = (sync_timeout if sync_timeout is not None
                         else _env_float(ENV_GROUP_SYNC_TIMEOUT,
                                         _DEFAULT_SYNC_TIMEOUT))
    self.miss_limit = (miss_limit if miss_limit is not None
                       else _env_int(ENV_GROUP_MISS_LIMIT,
                                     _DEFAULT_MISS_LIMIT))
    self.keep_rounds = keep_rounds
    self._now = time_fn
    self._lock = threading.Lock()
    self.active: set = set()
    self.lost: Dict[int, str] = {}          # gid -> reason
    self._ever: set = set()
    self._miss: Dict[int, int] = {}         # gid -> consecutive misses
    # round -> {"contrib": {gid: (weight, leaves)}, "need": set,
    #           "deadline": float, "t0": float, "merged": leaves|None,
    #           "members": [gid], "step": int}
    self._rounds: Dict[int, dict] = {}
    #: latest merged weights — the catch-up payload a (re)joining group
    #: pulls: {"round": int, "step": int, "payload": leaves}
    self.latest: Optional[dict] = None
    self.rounds_completed = 0
    self.last_sync_ms: Optional[float] = None
    self.step = 0                           # highest synced member step
    self.events: deque = deque(maxlen=256)

  # -- membership -------------------------------------------------------------

  def _event_locked(self, kind: str, **fields) -> None:
    self.events.append(dict(fields, event=kind, t=self._now()))
    logger.info("sync plane: %s %s", kind, fields)

  def join(self, gid: int) -> dict:
    with self._lock:
      fresh = gid not in self.active
      self.active.add(gid)
      self._ever.add(gid)
      self.lost.pop(gid, None)
      self._miss.pop(gid, None)
      if fresh:
        self._event_locked("join", group=gid, active=len(self.active))
      latest = self.latest
      return {"type": "GROUP", "ok": True, "active": sorted(self.active),
              "step": self.step,
              "round": latest["round"] if latest else -1,
              "payload": latest["payload"] if latest else None}

  def leave(self, gid: int) -> dict:
    with self._lock:
      self.active.discard(gid)
      self._miss.pop(gid, None)
      self._event_locked("leave", group=gid, active=len(self.active))
      return {"type": "GROUP", "ok": True, "active": sorted(self.active)}

  def mark_lost(self, gid: int, reason: str = "reported") -> None:
    """Commit the shrink: the group stops counting toward round
    completion and its future contributions are rejected until a
    re-join. Idempotent."""
    with self._lock:
      self._mark_lost_locked(gid, reason)

  def _mark_lost_locked(self, gid: int, reason: str) -> None:
    if gid in self.lost:
      return
    self.active.discard(gid)
    self._ever.add(gid)
    self.lost[gid] = reason
    self._miss.pop(gid, None)
    self._event_locked("lost", group=gid, reason=reason,
                       active=len(self.active))

  def seed(self, step: int, payload: Optional[List[dict]] = None) -> None:
    """Prime the plane after a checkpoint restore: the step counter (and
    optionally the restored weights as the catch-up payload for late
    joiners) continue from the checkpoint instead of zero."""
    with self._lock:
      self.step = max(self.step, int(step))
      if payload is not None:
        self.latest = {"round": -1, "step": int(step), "payload": payload}

  # -- rounds -----------------------------------------------------------------

  def contribute(self, gid: int, rnd: int, payload: List[dict],
                 weight: float = 1.0, step: int = 0) -> dict:
    with self._lock:
      if gid in self.lost:
        return {"type": "OK", "accepted": False, "lost": True,
                "reason": self.lost[gid]}
      if gid not in self.active:
        # an unknown contributor self-admits (first-round bootstrap);
        # members join explicitly so this is the exception path
        self.active.add(gid)
        self._ever.add(gid)
        self._event_locked("join", group=gid, active=len(self.active),
                           implicit=True)
      r = self._rounds.get(rnd)
      if r is None:
        now = self._now()
        r = self._rounds[rnd] = {
            "contrib": {}, "need": set(self.active),
            "deadline": now + self.sync_timeout, "t0": now,
            "merged": None, "members": [], "step": 0}
      r["contrib"][gid] = (float(weight), payload)
      r["step"] = max(r["step"], int(step))
      self._miss[gid] = 0
      return {"type": "OK", "accepted": True,
              "contributed": len(r["contrib"]),
              "need": sorted(r["need"] - self.lost.keys())}

  def poll(self, rnd: int) -> dict:
    with self._lock:
      r = self._rounds.get(rnd)
      if r is None:
        return {"type": "SYNC", "done": False, "round": rnd,
                "waiting_on": []}
      if r["merged"] is None:
        # membership is frozen at round creation (groups joining mid-round
        # participate from the NEXT boundary — they must not stall this
        # one), but losses committed mid-round shrink the wait immediately
        need = r["need"] - set(self.lost)
        have = set(r["contrib"])
        if (have and have >= need) or self._now() >= r["deadline"]:
          self._merge_locked(rnd, r, need)
      if r["merged"] is None:
        need = r["need"] - set(self.lost)
        return {"type": "SYNC", "done": False, "round": rnd,
                "waiting_on": sorted(need - set(r["contrib"]))}
      return {"type": "SYNC", "done": True, "round": rnd,
              "payload": r["merged"], "members": r["members"],
              "denominator": len(r["members"]), "step": r["step"]}

  def _merge_locked(self, rnd: int, r: dict, need: set) -> None:
    missing = sorted(need - set(r["contrib"]))
    for gid in missing:
      misses = self._miss[gid] = self._miss.get(gid, 0) + 1
      if misses >= self.miss_limit:
        self._mark_lost_locked(
            gid, "missed %d consecutive sync round(s)" % misses)
    members = sorted(r["contrib"])
    r["merged"] = merge_payloads([r["contrib"][g] for g in members])
    r["members"] = members
    now = self._now()
    self.last_sync_ms = (now - r["t0"]) * 1000.0
    self.rounds_completed += 1
    self.step = max(self.step, r["step"])
    self.latest = {"round": rnd, "step": r["step"], "payload": r["merged"]}
    self._event_locked("round", round=rnd, members=members,
                       missing=missing, step=r["step"],
                       sync_ms=round(self.last_sync_ms, 3))
    # contributions served their purpose; keep only the merged result,
    # and only for the last few rounds (stragglers polling an old round)
    r["contrib"] = {}
    for old in sorted(self._rounds):
      if old < rnd - self.keep_rounds:
        del self._rounds[old]

  # -- wire entry points ------------------------------------------------------

  def handle(self, msg: dict) -> dict:
    """Serve one SYNC/SYNCQ/GROUP message (the Server delegate)."""
    mtype = msg.get("type")
    if mtype == "SYNC":
      return self.contribute(int(msg["group_id"]), int(msg["round"]),
                             msg["payload"],
                             weight=msg.get("weight", 1.0),
                             step=msg.get("step", 0))
    if mtype == "SYNCQ":
      return self.poll(int(msg["round"]))
    if mtype == "GROUP":
      action = msg.get("action")
      gid = int(msg["group_id"]) if "group_id" in msg else None
      if action == "join":
        return self.join(gid)
      if action == "leave":
        return self.leave(gid)
      if action == "lost":
        self.mark_lost(gid, msg.get("reason", "reported"))
        return {"type": "GROUP", "ok": True, "active": sorted(self.active)}
      if action == "state":
        return dict(self.status(), type="GROUP", ok=True)
      return {"type": "ERROR", "error": "unknown GROUP action %r" % action}
    return {"type": "ERROR", "error": "sync plane cannot serve %r" % mtype}

  def status(self) -> dict:
    """Bounded topology summary for HEALTH replies / obs_top."""
    with self._lock:
      return {"active": sorted(self.active),
              "lost": sorted(self.lost),
              "groups_active": len(self.active),
              "groups_total": len(self._ever),
              "round": self.latest["round"] if self.latest else -1,
              "step": self.step,
              "rounds_completed": self.rounds_completed,
              "sync_ms": (round(self.last_sync_ms, 3)
                          if self.last_sync_ms is not None else None)}


def attach_sync_plane(server, **kwargs) -> SyncPlane:
  """Create a :class:`SyncPlane` and attach it to a rendezvous server
  (idempotent: returns the already-attached plane if present)."""
  plane = getattr(server, "sync_plane", None)
  if plane is None:
    plane = SyncPlane(**kwargs)
    server.sync_plane = plane
  return plane


# -- group-side client --------------------------------------------------------


class GroupSyncClient(object):
  """One group's handle on the sync plane. Every wait is bounded by an
  explicit deadline (TOS001): a plane that never completes a round
  surfaces as :class:`TimeoutError` here, never as a wedged group."""

  def __init__(self, server_addr: Tuple[str, int], group_id: int,
               request_timeout: float = 30.0):
    self.group_id = int(group_id)
    self._client = rendezvous.Client(tuple(server_addr),
                                     timeout=request_timeout)

  def join(self) -> dict:
    return self._client._request({"type": "GROUP", "action": "join",
                                  "group_id": self.group_id})

  def leave(self) -> dict:
    return self._client._request({"type": "GROUP", "action": "leave",
                                  "group_id": self.group_id})

  def report_lost(self, group_id: int, reason: str = "reported") -> dict:
    return self._client._request({"type": "GROUP", "action": "lost",
                                  "group_id": int(group_id),
                                  "reason": reason})

  def state(self) -> dict:
    return self._client._request({"type": "GROUP", "action": "state",
                                  "group_id": self.group_id})

  def sync(self, round_num: int, tree: Any, weight: float = 1.0,
           step: int = 0, timeout: float = 60.0,
           poll_interval: float = 0.02) -> Tuple[Any, List[int]]:
    """Contribute ``tree`` to ``round_num`` and block (bounded) for the
    merged result: ``(merged_tree, member_gids)``.

    Raises :class:`GroupEvicted` when the plane marked this group lost —
    the caller must re-:meth:`join` (pulling current weights) before its
    next sync. Raises :class:`TimeoutError` past ``timeout``.
    """
    payload = pack_tree(tree)
    resp = self._client._request(
        {"type": "SYNC", "group_id": self.group_id, "round": int(round_num),
         "payload": payload, "weight": float(weight), "step": int(step)})
    if resp.get("lost"):
      raise GroupEvicted("group %d evicted from the sync plane (%s)"
                         % (self.group_id, resp.get("reason")))
    deadline = time.monotonic() + max(0.0, timeout)
    while True:
      resp = self._client._request({"type": "SYNCQ",
                                    "round": int(round_num)})
      if resp.get("done"):
        return (unpack_tree(resp["payload"], tree),
                [int(g) for g in resp.get("members", [])])
      if time.monotonic() >= deadline:
        raise TimeoutError(
            "sync round %d did not complete within %.1fs (waiting on %s)"
            % (round_num, timeout, resp.get("waiting_on")))
      time.sleep(poll_interval)

  def close(self) -> None:
    try:
      self._client.close()
    except Exception:  # noqa: BLE001 - best-effort socket teardown
      pass


# -- the in-process group runtime --------------------------------------------


class TrainGroup(object):
  """One mesh group: a private fused TrainLoop over a device subset,
  stepping independently between sync boundaries."""

  def __init__(self, group_id: int, state: Any, loop, sync: GroupSyncClient,
               steps: int = 0):
    self.group_id = int(group_id)
    self.state = state
    self.loop = loop
    self.sync = sync
    self.steps = int(steps)
    self.losses: List[float] = []
    self.alive = True
    self.exit_reason: Optional[str] = None
    self.sync_ms: Optional[float] = None
    self.thread: Optional[threading.Thread] = None


class GroupSet(object):
  """N interchangeable mesh groups training one model (see module doc).

  ``build_fn(mesh) -> (state, loss_fn)`` constructs each group's initial
  train state and loss on its mesh (every group must build the SAME
  structure — interchangeability is the contract). ``batch_fn(group_id,
  step) -> batch`` supplies deterministic per-group data; because it is
  keyed by ``(group_id, step)``, the data-feed position IS the step
  counter, so a resharded restore resumes the feed for free.

  Same-process topology (threads over device subsets) matches the
  serving fleet's replicas: the elasticity mechanics — membership,
  rounds, eviction, catch-up — are identical for cross-process groups,
  which only swap the transport endpoint (the rendezvous address).
  """

  def __init__(self, build_fn: Callable, batch_fn: Callable,
               num_groups: int, sync_every: Optional[int] = None,
               sync_timeout: Optional[float] = None,
               miss_limit: Optional[int] = None,
               unroll: Optional[int] = None,
               devices_per_group: int = 1,
               server: Optional[rendezvous.Server] = None):
    if num_groups < 1:
      raise ValueError("need at least one group")
    self.build_fn = build_fn
    self.batch_fn = batch_fn
    self.sync_every = (sync_every if sync_every is not None
                       else _env_int(ENV_GROUP_SYNC_EVERY,
                                     _DEFAULT_SYNC_EVERY))
    self.sync_timeout = (sync_timeout if sync_timeout is not None
                         else _env_float(ENV_GROUP_SYNC_TIMEOUT,
                                         _DEFAULT_SYNC_TIMEOUT))
    self.unroll = unroll
    self.devices_per_group = max(1, int(devices_per_group))
    self._own_server = server is None
    if server is None:
      server = rendezvous.Server(1)
      server.start()
    self.server = server
    self.plane = attach_sync_plane(server, sync_timeout=self.sync_timeout,
                                   miss_limit=miss_limit)
    self.groups: Dict[int, TrainGroup] = {}
    self.events: deque = deque(maxlen=256)
    self._plane_events_seen = 0
    self._stop = threading.Event()
    self._total: Optional[int] = None
    self._lock = threading.Lock()
    for gid in range(num_groups):
      self.groups[gid] = self._make_group(gid)
    self._publish_telemetry()

  # -- construction -----------------------------------------------------------

  def _mesh_for(self, gid: int):
    import jax
    from tensorflowonspark_tpu.parallel import mesh as mesh_lib
    devs = jax.devices()
    k = min(self.devices_per_group, len(devs))
    start = (gid * k) % len(devs)
    picked = [devs[(start + i) % len(devs)] for i in range(k)]
    return mesh_lib.build_mesh(mesh_lib.MeshSpec(data=-1), devices=picked)

  def _make_group(self, gid: int) -> TrainGroup:
    from tensorflowonspark_tpu.parallel import sharding as SH
    mesh = self._mesh_for(gid)
    state, loss_fn = self.build_fn(mesh)
    # donation off: the group re-reads its state at sync boundaries (to
    # pack the params) after the loop call that produced it
    loop = SH.make_train_loop(loss_fn, mesh, unroll=self.unroll,
                              donate_state=False)
    sync = GroupSyncClient(self.server.addr, gid,
                           request_timeout=max(5.0, self.sync_timeout))
    group = TrainGroup(gid, state, loop, sync)
    # GROUP-verb join: a rendezvous request bounded by the client's
    # request timeout, not a thread join
    resp = sync.join()  # tosa: ignore[TOS001] - request-timeout bounded
    payload = resp.get("payload")
    if payload is not None:
      # catch-up: a (re)admitted group adopts the collective's current
      # weights and step so it rejoins at the next boundary as a peer
      group.state = group.state.replace(
          params=unpack_tree(payload, group.state.params))
      group.steps = int(resp.get("step", 0))
      self._event("catch-up", group=gid, step=group.steps)
    return group

  # -- events + telemetry -----------------------------------------------------

  def _event(self, kind: str, **fields) -> None:
    rec = dict(fields, event=kind, t=time.monotonic())
    self.events.append(rec)
    logger.info("groupset: %s %s", kind, fields)
    rec_obs = obs_spans.active()
    if rec_obs is not None:
      rec_obs.event("groups." + kind,
                    **{k: v for k, v in fields.items()
                       if isinstance(v, (int, float, str, bool))})

  def _drain_plane_events(self) -> None:
    events = list(self.plane.events)
    for rec in events[self._plane_events_seen:]:
      kind = rec.get("event")
      if kind in ("lost", "round"):
        self._event("plane-" + kind,
                    **{k: v for k, v in rec.items()
                       if k not in ("event", "t")})
    self._plane_events_seen = len(events)

  def _publish_telemetry(self) -> None:
    reg = obs_metrics.active()
    if reg is None:
      return
    status = self.plane.status()
    reg.gauge("training.groups_total").set(
        max(status["groups_total"], len(self.groups)))
    reg.gauge("training.groups_active").set(status["groups_active"])
    if status["sync_ms"] is not None:
      reg.gauge("training.sync_ms").set(status["sync_ms"])

  # -- the per-group loop -----------------------------------------------------

  def _group_main(self, g: TrainGroup, total_steps: int) -> None:
    try:
      while (g.alive and g.steps < total_steps
             and not self._stop.is_set()):
        verdict = chaos.group_fault(g.group_id)
        if verdict == "kill":
          # the whole group dies mid-training: no contribution, no
          # goodbye — the plane discovers it via the round deadline
          g.alive = False
          g.exit_reason = "chaos-kill"
          self._event("group-killed", group=g.group_id, step=g.steps)
          return
        import numpy as np
        n = min(self.sync_every or total_steps, total_steps - g.steps)
        for _ in range(n):
          batch = self.batch_fn(g.group_id, g.steps)
          g.state, losses = g.loop(g.state, batch)
          g.steps += 1
          g.losses.extend(float(v) for v in np.asarray(losses).reshape(-1))
        if not self.sync_every:
          continue          # sync disabled (single-group baseline)
        rnd = g.steps // self.sync_every
        t0 = time.monotonic()
        try:
          merged, members = g.sync.sync(
              rnd, g.state.params, weight=n, step=g.steps,
              timeout=self.sync_timeout + 10.0)
        except GroupEvicted:
          # marked lost while stalled/partitioned: stale weights were
          # rejected — re-admit via join (adopting current weights+step)
          resp = g.sync.join()  # tosa: ignore[TOS001] - request-timeout bounded
          payload = resp.get("payload")
          if payload is not None:
            g.state = g.state.replace(
                params=unpack_tree(payload, g.state.params))
            g.steps = int(resp.get("step", g.steps))
          self._event("group-readmitted", group=g.group_id, step=g.steps)
          continue
        except (TimeoutError, ConnectionError) as e:
          g.alive = False
          g.exit_reason = "sync-failed: %s" % e
          self._event("group-sync-failed", group=g.group_id,
                      step=g.steps, error=str(e))
          return
        g.sync_ms = (time.monotonic() - t0) * 1000.0
        g.state = g.state.replace(params=merged)
        self._event("sync", group=g.group_id, round=rnd, step=g.steps,
                    denominator=len(members),
                    sync_ms=round(g.sync_ms, 3))
        self._drain_plane_events()
        self._publish_telemetry()
      if g.alive:
        g.exit_reason = "completed"
    except Exception as e:  # noqa: BLE001 - a group failure must surface
      # as a lost group, never as a silent thread death
      g.alive = False
      g.exit_reason = "error: %s" % e
      logger.exception("group %d failed", g.group_id)
      self._event("group-error", group=g.group_id, error=str(e))

  def run(self, total_steps: int) -> None:
    """Start every group stepping toward ``total_steps`` (returns
    immediately; :meth:`wait` joins)."""
    self._total = int(total_steps)
    for g in self.groups.values():
      self._spawn(g)

  def _spawn(self, g: TrainGroup) -> None:
    g.thread = threading.Thread(
        target=self._group_main, args=(g, self._total),
        name="train-group-%d" % g.group_id, daemon=True)
    g.thread.start()

  def wait(self, timeout: float = 300.0) -> bool:
    """Join all group threads (bounded). True when every thread ended."""
    deadline = time.monotonic() + timeout
    done = True
    for g in list(self.groups.values()):
      if g.thread is None:
        continue
      g.thread.join(max(0.0, deadline - time.monotonic()))
      done = done and not g.thread.is_alive()
    self._drain_plane_events()
    self._publish_telemetry()
    return done

  def stop(self) -> None:
    self._stop.set()

  def close(self) -> None:
    self.stop()
    for g in self.groups.values():
      g.sync.close()
    if self._own_server:
      self.server.stop()

  # -- elasticity -------------------------------------------------------------

  def readmit(self, gid: int) -> TrainGroup:
    """Bring a lost (or brand-new) group back: build it fresh, pull the
    current weights/step from the plane (the join catch-up), and start it
    stepping toward the same target — it participates from the next sync
    boundary. Scale-up (``grow``) is the same operation with a new id."""
    with self._lock:
      old = self.groups.get(gid)
      if old is not None and old.thread is not None \
          and old.thread.is_alive():
        raise RuntimeError("group %d is still running" % gid)
      g = self._make_group(gid)
      self.groups[gid] = g
    self._event("group-readmitted", group=gid, step=g.steps)
    self._publish_telemetry()
    if self._total is not None:
      self._spawn(g)
    return g

  grow = readmit

  def commit_shrink(self, gid: int, reason: str = "shrink committed") -> None:
    """Give up on a group: evict it from the plane so rounds never wait
    for it and its stale contributions are rejected."""
    self.plane.mark_lost(gid, reason)
    self._event("resize-shrink", group=gid, reason=reason)
    self._drain_plane_events()
    self._publish_telemetry()

  def active_groups(self) -> List[int]:
    return sorted(g.group_id for g in self.groups.values() if g.alive)

  # -- checkpoint plane (topology-manifested save / resharding restore) -------

  def _chief(self) -> TrainGroup:
    alive = [g for g in self.groups.values() if g.alive]
    if not alive:
      raise RuntimeError("no live group to checkpoint")
    return min(alive, key=lambda g: g.group_id)

  def manifest(self) -> dict:
    chief = self._chief()
    return {"schema": 1, "kind": "groupset",
            "num_groups": len(self.active_groups()),
            "groups": self.active_groups(),
            "step": chief.steps,
            "sync_every": self.sync_every,
            "sync_round": (chief.steps // self.sync_every
                           if self.sync_every else 0)}

  def save(self, mgr, force: bool = False) -> bool:
    """Chief-group save with the group topology in the commit manifest.

    Call at a sync boundary: post-sync, every group's params are the
    merged weights, so the chief's state IS the collective state and any
    future group count can restore from it (interchangeability again).
    """
    chief = self._chief()
    saved = mgr.save(chief.steps, chief.state, force=force,
                     manifest=self.manifest())
    if saved:
      self._event("checkpoint", step=chief.steps,
                  groups=len(self.active_groups()))
    return saved

  def restore_or(self, mgr) -> int:
    """Restore the latest committed checkpoint INTO THIS topology —
    resharding across a different group count — and return the next
    step (0 when starting fresh).

    Every group adopts the restored state and step counter (data-parallel
    groups hold replicated weights at boundaries, so a topology change is
    a broadcast, not a re-partition); the plane is seeded so later
    joiners catch up to the restored step, and ``batch_fn(group_id,
    step)`` keying makes the feed position follow the step for free.
    """
    chief = self._chief()
    state, next_step, manifest = mgr.restore_or(chief.state,
                                                with_manifest=True)
    if next_step == 0:
      return 0
    saved_step = next_step - 1
    if manifest and manifest.get("num_groups") not in (
        None, len(self.groups)):
      logger.info(
          "resharding checkpoint step %d across %d group(s) (saved with "
          "%d)", saved_step, len(self.groups), manifest["num_groups"])
    for g in self.groups.values():
      g.state = state
      g.steps = saved_step
    self.plane.seed(saved_step, pack_tree(state.params))
    self._event("restore", step=saved_step, groups=len(self.groups),
                saved_groups=(manifest or {}).get("num_groups"))
    return next_step
