"""Sharding rules + the SPMD train-step factory.

This is the TPU-native analog of the reference wiring a
``MultiWorkerMirroredStrategy`` from TF_CONFIG (e.g. reference
examples/mnist/keras/mnist_spark.py:11): one call produces a jitted train
step whose parameters and batch are laid out over the mesh, with gradient
all-reduce (DP), parameter sharding (TP/FSDP) and activation sharding
compiled by XLA into ICI collectives.

Parameter placement uses flax logical-axis rules: modules annotate
``nn.with_partitioning`` / logical names, and ``LOGICAL_RULES`` maps those
names onto mesh axes.
"""

import logging
import os
import time
from typing import Callable, Optional, Tuple

from tensorflowonspark_tpu.obs import device as obs_device
from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.parallel import mesh as mesh_lib

logger = logging.getLogger(__name__)

#: default unroll for :func:`make_train_loop` — how many optimizer steps
#: one dispatch fuses. 1 = the status-quo per-step path. Set by
#: ``cluster.run(train_unroll=K)`` on every node (env registry: TOS008)
ENV_TRAIN_UNROLL = "TOS_TRAIN_UNROLL"


def resolve_unroll(unroll: Optional[int] = None) -> int:
  """The effective train-loop unroll: explicit argument beats the
  ``TOS_TRAIN_UNROLL`` env (which ``cluster.run(train_unroll=K)`` exports
  into every node process); default 1 — the per-step status quo.

  Env values that don't name a usable K (malformed, empty, ``0`` — the
  CLI convention for "per-step") resolve to 1 rather than raising: an
  env typo must not crash every node's main fn. An EXPLICIT ``unroll``
  argument < 1 is a caller bug and raises.
  """
  if unroll is None:
    try:
      unroll = int(os.environ.get(ENV_TRAIN_UNROLL, "1"))
    except ValueError:
      unroll = 1
    return max(1, unroll)
  if unroll < 1:
    raise ValueError("train unroll must be >= 1, got %d" % unroll)
  return int(unroll)

# logical axis name -> mesh axis (None = replicated)
LOGICAL_RULES = (
    ("batch", (mesh_lib.AXIS_DATA, mesh_lib.AXIS_FSDP)),
    ("sequence", mesh_lib.AXIS_SEQUENCE),
    ("vocab", mesh_lib.AXIS_TENSOR),
    ("embed", mesh_lib.AXIS_FSDP),
    ("heads", mesh_lib.AXIS_TENSOR),
    ("kv", None),
    ("mlp", mesh_lib.AXIS_TENSOR),
    ("stage", mesh_lib.AXIS_PIPELINE),
    ("expert", mesh_lib.AXIS_EXPERT),
    ("conv_in", None),
    ("conv_out", mesh_lib.AXIS_TENSOR),
)


def batch_sharding(mesh, extra_axes: Tuple[str, ...] = ()):
  """NamedSharding placing dim 0 of a batch over the data(/fsdp) axes and,
  optionally, dim 1 over the sequence axis."""
  from jax.sharding import NamedSharding, PartitionSpec as P
  dims = [mesh_lib.data_axes(mesh) or None]
  dims.extend(extra_axes)
  return NamedSharding(mesh, P(*dims))


def replicated(mesh):
  from jax.sharding import NamedSharding, PartitionSpec as P
  return NamedSharding(mesh, P())


def logical_to_mesh_sharding(logical_specs, mesh):
  """Apply LOGICAL_RULES to a pytree of flax logical PartitionSpecs."""
  import flax.linen as nn
  return nn.logical_to_mesh_sharding(logical_specs, mesh,
                                     rules=LOGICAL_RULES)


def param_sharding_from_boxed(boxed_params, mesh):
  """Sharding tree from flax ``Partitioned``-boxed params (as returned by
  ``model.init`` when modules use ``with_logical_partitioning``)."""
  import jax
  import flax.linen as nn
  from jax.sharding import NamedSharding, PartitionSpec as P

  logical = nn.get_partition_spec(boxed_params)
  shardings = logical_to_mesh_sharding(logical, mesh)

  def _fix(leaf):
    return leaf if isinstance(leaf, NamedSharding) else NamedSharding(mesh, P())

  return jax.tree.map(_fix, shardings,
                      is_leaf=lambda x: isinstance(x, NamedSharding)
                      or x is None)


def state_shardings(abs_state, param_sharding, mesh):
  """Shardings for a whole TrainState: params exact, optimizer moments
  mirror THEIR parameter (matched by tree path, so two same-shaped params
  with different layouts keep their own moment layouts — a shape-keyed
  lookup would silently reshard one of them every step), everything else
  replicated."""
  import jax
  from jax.sharding import NamedSharding, PartitionSpec as P
  from jax.tree_util import tree_flatten_with_path, tree_unflatten

  def _names(path):
    return tuple(str(getattr(k, "key", getattr(k, "name",
                                               getattr(k, "idx", k))))
                 for k in path)

  param_flat, _ = tree_flatten_with_path(abs_state.params)
  by_path = {}
  for (path, leaf), sh in zip(param_flat, jax.tree.leaves(param_sharding)):
    by_path[_names(path)] = (tuple(leaf.shape), sh)

  state_flat, treedef = tree_flatten_with_path(abs_state)
  out = []
  for path, leaf in state_flat:
    names = _names(path)
    sh = None
    if getattr(leaf, "ndim", 0) > 0:
      # optimizer moments live at <state prefix> + <param path>: take the
      # longest path suffix that names a parameter of the same shape
      for i in range(len(names)):
        hit = by_path.get(names[i:])
        if hit is not None and hit[0] == tuple(getattr(leaf, "shape", ())):
          sh = hit[1]
          break
    out.append(sh if sh is not None else NamedSharding(mesh, P()))
  full = tree_unflatten(treedef, out)
  return full.replace(params=param_sharding)


def init_sharded_state(params_init_fn: Callable, make_state_fn: Callable,
                       mesh):
  """Initialize a TrainState directly sharded over ``mesh``.

  ``params_init_fn()`` returns flax ``model.init(...)``'s (possibly
  Partitioned-boxed) params; ``make_state_fn(unboxed_params)`` wraps them in
  a TrainState (running the optimizer init). Uses eval_shape +
  jit(out_shardings=...) so even the initializers run sharded — parameters
  larger than one host's memory never materialize unsharded.

  Returns (state, state_sharding).
  """
  import jax
  from flax.core import meta

  def _full_init():
    return make_state_fn(meta.unbox(params_init_fn()))

  abs_boxed = jax.eval_shape(params_init_fn)
  param_sharding = param_sharding_from_boxed(abs_boxed, mesh)
  abs_state = jax.eval_shape(_full_init)
  sharding = state_shardings(abs_state, param_sharding, mesh)
  state = jax.jit(_full_init, out_shardings=sharding)()
  return state, sharding


def make_train_step(loss_fn: Callable,
                    mesh,
                    state_sharding=None,
                    donate_state: bool = True,
                    batch_extra_axes: Tuple[str, ...] = ()):
  """Build a jitted SPMD train step: ``step(state, batch) -> (state, loss)``.

  ``loss_fn(params, batch)`` must be pure. The batch is sharded over
  data/fsdp (plus ``batch_extra_axes``, e.g. ("sequence",) for
  sequence-parallel inputs); parameters/optimizer follow ``state_sharding``
  (from :func:`init_sharded_state`) or are replicated when None. XLA compiles
  the gradient sync to ICI collectives.
  """
  import jax

  batch_shard = batch_sharding(mesh, batch_extra_axes)

  def _step(state, batch):
    # recompile sentinel seam (obs/device.py): a steady-state train loop
    # must never re-trace this — pinned by the recompile-sentinel test
    obs_device.note_trace("train.step")
    loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
    return state.apply_gradients(grads=grads), loss

  kw = {}
  if state_sharding is not None:
    kw = dict(in_shardings=(state_sharding, batch_shard),
              out_shardings=(state_sharding, replicated(mesh)))
  step = jax.jit(_step, donate_argnums=(0,) if donate_state else (), **kw)
  if not obs_device.device_tier_enabled():
    return step

  # device tier on: capture the train step's HLO cost (flops / bytes
  # accessed) at first call. The wrapper adds one dict check per step and
  # keeps the jit's AOT surface (.lower) for mosaic_gate-style callers.
  pending = {"capture": True}

  def step_with_cost(state, batch):
    if pending["capture"]:
      pending["capture"] = False
      obs_device.capture_cost("train.step", step, state, batch)
    return step(state, batch)

  step_with_cost.lower = step.lower
  return step_with_cost


def slab_sharding(mesh, extra_axes: Tuple[str, ...] = ()):
  """NamedSharding for a ``[K, B, ...]`` batch slab: the leading (scan)
  dim replicated, dim 1 over data/fsdp (plus ``extra_axes`` from dim 2)
  — the slab analog of :func:`batch_sharding`."""
  from jax.sharding import NamedSharding, PartitionSpec as P
  dims = [None, mesh_lib.data_axes(mesh) or None]
  dims.extend(extra_axes)
  return NamedSharding(mesh, P(*dims))


class TrainLoop(object):
  """Callable built by :func:`make_train_loop`: per-step and fused paths
  behind one dispatch surface.

  ``loop(state, item) -> (state, losses)`` where ``item`` is either a
  plain batch (one optimizer step; ``losses`` has shape ``[1]``) or a
  :class:`data.readers.Slab` of ``unroll`` stacked batches (one fused
  ``lax.scan`` dispatch; ``losses`` has shape ``[unroll]``, reduced on
  device and fetched once per slab). ``loop.steps`` counts optimizer
  steps taken host-side — the step-accurate value to hand to
  ``CheckpointManager.save`` at slab boundaries.
  """

  def __init__(self, step_fn, fused_fn, unroll: int, obs_handles):
    self._step = step_fn
    self._fused = fused_fn
    self.unroll = unroll
    #: optimizer steps dispatched through this loop (host-side count)
    self.steps = 0
    self._obs = obs_handles      # None, or (counter, recorder-or-None)

  def _record(self, n: int, t0: float) -> None:
    self.steps += n
    if self._obs is None:
      return
    counter, rec = self._obs
    counter.inc(n)
    if rec is not None:
      rec.record_span("train.slab", t0, time.monotonic() - t0, steps=n)

  @staticmethod
  def _unstack(slab_data):
    import jax
    leaves = jax.tree.leaves(slab_data)
    n = leaves[0].shape[0] if leaves else 0
    return [jax.tree.map(lambda x, i=i: x[i], slab_data) for i in range(n)]

  def _per_step(self, state, batches, t0: float):
    import jax.numpy as jnp
    losses = []
    for batch in batches:
      state, loss = self._step(state, batch)
      losses.append(loss)
    self._record(len(losses), t0)
    return state, jnp.stack(losses) if losses else jnp.zeros((0,))

  def __call__(self, state, item):
    from tensorflowonspark_tpu.data.readers import Slab
    t0 = time.monotonic()
    if isinstance(item, Slab):
      import jax
      leaves = jax.tree.leaves(item.data)
      k = leaves[0].shape[0] if leaves else 0
      if self._fused is not None and k == self.unroll:
        state, losses = self._fused(state, item.data)
        self._record(self.unroll, t0)
        return state, losses
      # a slab that doesn't match the fused shape (partial tail that was
      # stacked anyway, or unroll=1): the per-step jit entry serves it
      return self._per_step(state, self._unstack(item.data), t0)
    return self._per_step(state, [item], t0)


def make_train_loop(loss_fn: Callable,
                    mesh,
                    state_sharding=None,
                    donate_state: bool = True,
                    batch_extra_axes: Tuple[str, ...] = (),
                    unroll: Optional[int] = None) -> TrainLoop:
  """Build a dispatch-amortized train loop: ``unroll`` optimizer steps
  fused into one jitted ``lax.scan`` over a ``[unroll, B, ...]`` slab.

  The per-step path (``make_train_step``) pays one host dispatch, one
  host→device transfer and one metrics sync per optimizer step; at small
  step times that overhead dominates (the serving side proved the same
  amortization with its decode horizon). The fused path scans the SAME
  step body over a slab of ``unroll`` stacked batches with the state
  donated, so K steps ride one dispatch and the ``[unroll]`` loss vector
  is fetched once per slab.

  Contract (pinned by tests): same batch order in ⇒ bit-identical
  loss/param trajectory vs the per-step path — ``optax.MultiSteps``
  grad-accum included (``state.tx`` is applied once per scanned step,
  exactly as the per-step path applies it). The jit cache stays at
  exactly two entries: the fused ``[unroll, B, ...]`` scan and the
  ``[B, ...]`` per-step fallback that partial final slabs ride.

  ``unroll=None`` reads ``TOS_TRAIN_UNROLL`` (exported into every node
  by ``cluster.run(train_unroll=K)``); 1 keeps the per-step status quo
  with the same calling convention. Feed slabs with
  ``data.readers.slab_batches(feed, B, unroll)`` composed with
  ``device_prefetch`` so slab k+1 transfers under slab k's compute.
  """
  import jax
  from jax import lax

  unroll = resolve_unroll(unroll)
  step = make_train_step(loss_fn, mesh, state_sharding,
                         donate_state=donate_state,
                         batch_extra_axes=batch_extra_axes)

  fused = None
  if unroll > 1:
    slab_shard = slab_sharding(mesh, batch_extra_axes)

    def _loop(state, slab):
      # recompile sentinel seam: a steady-state fused loop must never
      # re-trace this (obs/device.py; same pin as the per-step seam)
      obs_device.note_trace("train.loop")

      def body(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        return state.apply_gradients(grads=grads), loss

      return lax.scan(body, state, slab)

    kw = {}
    if state_sharding is not None:
      kw = dict(in_shardings=(state_sharding, slab_shard),
                out_shardings=(state_sharding, replicated(mesh)))
    fused = jax.jit(_loop, donate_argnums=(0,) if donate_state else (),
                    **kw)
    if obs_device.device_tier_enabled():
      inner, pending = fused, {"capture": True}

      def fused_with_cost(state, slab):
        if pending["capture"]:
          pending["capture"] = False
          obs_device.capture_cost("train.loop", inner, state, slab)
        return inner(state, slab)

      fused_with_cost.lower = inner.lower
      fused = fused_with_cost

  obs_handles = None
  reg = obs_metrics.active()
  if reg is not None:
    # the loop owns the step accounting the detectors read: train.steps
    # bumps by K per fused dispatch (bursts — obs/anomaly.py discounts
    # one-slab quantization via this gauge), train.slab spans each
    # dispatch. Don't ALSO wrap loop calls in a StepTimer, or steps
    # double-count.
    reg.gauge("train.unroll").set(unroll)
    obs_handles = (reg.counter("train.steps"), obs_spans.active())
  return TrainLoop(step, fused, unroll, obs_handles)


def shard_batch(batch, mesh, extra_axes: Tuple[str, ...] = ()):
  """Place a host batch onto the mesh with batch sharding."""
  import jax
  sharding = batch_sharding(mesh, extra_axes)
  return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
