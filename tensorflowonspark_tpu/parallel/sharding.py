"""Sharding rules + the SPMD train-step factory.

This is the TPU-native analog of the reference wiring a
``MultiWorkerMirroredStrategy`` from TF_CONFIG (e.g. reference
examples/mnist/keras/mnist_spark.py:11): one call produces a jitted train
step whose parameters and batch are laid out over the mesh, with gradient
all-reduce (DP), parameter sharding (TP/FSDP) and activation sharding
compiled by XLA into ICI collectives.

Parameter placement uses flax logical-axis rules: modules annotate
``nn.with_partitioning`` / logical names, and ``LOGICAL_RULES`` maps those
names onto mesh axes.
"""

import logging
from typing import Callable, Tuple

from tensorflowonspark_tpu.obs import device as obs_device
from tensorflowonspark_tpu.parallel import mesh as mesh_lib

logger = logging.getLogger(__name__)

# logical axis name -> mesh axis (None = replicated)
LOGICAL_RULES = (
    ("batch", (mesh_lib.AXIS_DATA, mesh_lib.AXIS_FSDP)),
    ("sequence", mesh_lib.AXIS_SEQUENCE),
    ("vocab", mesh_lib.AXIS_TENSOR),
    ("embed", mesh_lib.AXIS_FSDP),
    ("heads", mesh_lib.AXIS_TENSOR),
    ("kv", None),
    ("mlp", mesh_lib.AXIS_TENSOR),
    ("stage", mesh_lib.AXIS_PIPELINE),
    ("expert", mesh_lib.AXIS_EXPERT),
    ("conv_in", None),
    ("conv_out", mesh_lib.AXIS_TENSOR),
)


def batch_sharding(mesh, extra_axes: Tuple[str, ...] = ()):
  """NamedSharding placing dim 0 of a batch over the data(/fsdp) axes and,
  optionally, dim 1 over the sequence axis."""
  from jax.sharding import NamedSharding, PartitionSpec as P
  dims = [mesh_lib.data_axes(mesh) or None]
  dims.extend(extra_axes)
  return NamedSharding(mesh, P(*dims))


def replicated(mesh):
  from jax.sharding import NamedSharding, PartitionSpec as P
  return NamedSharding(mesh, P())


def logical_to_mesh_sharding(logical_specs, mesh):
  """Apply LOGICAL_RULES to a pytree of flax logical PartitionSpecs."""
  import flax.linen as nn
  return nn.logical_to_mesh_sharding(logical_specs, mesh,
                                     rules=LOGICAL_RULES)


def param_sharding_from_boxed(boxed_params, mesh):
  """Sharding tree from flax ``Partitioned``-boxed params (as returned by
  ``model.init`` when modules use ``with_logical_partitioning``)."""
  import jax
  import flax.linen as nn
  from jax.sharding import NamedSharding, PartitionSpec as P

  logical = nn.get_partition_spec(boxed_params)
  shardings = logical_to_mesh_sharding(logical, mesh)

  def _fix(leaf):
    return leaf if isinstance(leaf, NamedSharding) else NamedSharding(mesh, P())

  return jax.tree.map(_fix, shardings,
                      is_leaf=lambda x: isinstance(x, NamedSharding)
                      or x is None)


def state_shardings(abs_state, param_sharding, mesh):
  """Shardings for a whole TrainState: params exact, optimizer moments
  mirror THEIR parameter (matched by tree path, so two same-shaped params
  with different layouts keep their own moment layouts — a shape-keyed
  lookup would silently reshard one of them every step), everything else
  replicated."""
  import jax
  from jax.sharding import NamedSharding, PartitionSpec as P
  from jax.tree_util import tree_flatten_with_path, tree_unflatten

  def _names(path):
    return tuple(str(getattr(k, "key", getattr(k, "name",
                                               getattr(k, "idx", k))))
                 for k in path)

  param_flat, _ = tree_flatten_with_path(abs_state.params)
  by_path = {}
  for (path, leaf), sh in zip(param_flat, jax.tree.leaves(param_sharding)):
    by_path[_names(path)] = (tuple(leaf.shape), sh)

  state_flat, treedef = tree_flatten_with_path(abs_state)
  out = []
  for path, leaf in state_flat:
    names = _names(path)
    sh = None
    if getattr(leaf, "ndim", 0) > 0:
      # optimizer moments live at <state prefix> + <param path>: take the
      # longest path suffix that names a parameter of the same shape
      for i in range(len(names)):
        hit = by_path.get(names[i:])
        if hit is not None and hit[0] == tuple(getattr(leaf, "shape", ())):
          sh = hit[1]
          break
    out.append(sh if sh is not None else NamedSharding(mesh, P()))
  full = tree_unflatten(treedef, out)
  return full.replace(params=param_sharding)


def init_sharded_state(params_init_fn: Callable, make_state_fn: Callable,
                       mesh):
  """Initialize a TrainState directly sharded over ``mesh``.

  ``params_init_fn()`` returns flax ``model.init(...)``'s (possibly
  Partitioned-boxed) params; ``make_state_fn(unboxed_params)`` wraps them in
  a TrainState (running the optimizer init). Uses eval_shape +
  jit(out_shardings=...) so even the initializers run sharded — parameters
  larger than one host's memory never materialize unsharded.

  Returns (state, state_sharding).
  """
  import jax
  from flax.core import meta

  def _full_init():
    return make_state_fn(meta.unbox(params_init_fn()))

  abs_boxed = jax.eval_shape(params_init_fn)
  param_sharding = param_sharding_from_boxed(abs_boxed, mesh)
  abs_state = jax.eval_shape(_full_init)
  sharding = state_shardings(abs_state, param_sharding, mesh)
  state = jax.jit(_full_init, out_shardings=sharding)()
  return state, sharding


def make_train_step(loss_fn: Callable,
                    mesh,
                    state_sharding=None,
                    donate_state: bool = True,
                    batch_extra_axes: Tuple[str, ...] = ()):
  """Build a jitted SPMD train step: ``step(state, batch) -> (state, loss)``.

  ``loss_fn(params, batch)`` must be pure. The batch is sharded over
  data/fsdp (plus ``batch_extra_axes``, e.g. ("sequence",) for
  sequence-parallel inputs); parameters/optimizer follow ``state_sharding``
  (from :func:`init_sharded_state`) or are replicated when None. XLA compiles
  the gradient sync to ICI collectives.
  """
  import jax

  batch_shard = batch_sharding(mesh, batch_extra_axes)

  def _step(state, batch):
    # recompile sentinel seam (obs/device.py): a steady-state train loop
    # must never re-trace this — pinned by the recompile-sentinel test
    obs_device.note_trace("train.step")
    loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
    return state.apply_gradients(grads=grads), loss

  kw = {}
  if state_sharding is not None:
    kw = dict(in_shardings=(state_sharding, batch_shard),
              out_shardings=(state_sharding, replicated(mesh)))
  step = jax.jit(_step, donate_argnums=(0,) if donate_state else (), **kw)
  if not obs_device.device_tier_enabled():
    return step

  # device tier on: capture the train step's HLO cost (flops / bytes
  # accessed) at first call. The wrapper adds one dict check per step and
  # keeps the jit's AOT surface (.lower) for mosaic_gate-style callers.
  pending = {"capture": True}

  def step_with_cost(state, batch):
    if pending["capture"]:
      pending["capture"] = False
      obs_device.capture_cost("train.step", step, state, batch)
    return step(state, batch)

  step_with_cost.lower = step.lower
  return step_with_cost


def shard_batch(batch, mesh, extra_axes: Tuple[str, ...] = ()):
  """Place a host batch onto the mesh with batch sharding."""
  import jax
  sharding = batch_sharding(mesh, extra_axes)
  return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
