"""Ring attention: sequence/context parallelism for long sequences.

A first-class capability of this framework that the reference lacked
entirely (SURVEY.md §5 "Long-context / sequence parallelism: absent") — on
TPU it is what makes the ``sequence`` mesh axis real: Q stays resident per
shard while K/V blocks rotate around the ICI ring (``lax.ppermute``), with a
numerically-stable online-softmax accumulation so the result is exactly
full attention over the global sequence.

Compute cost per device: n_steps × block attention; communication overlaps
with compute because each step's ppermute of the *next* KV block is
independent of the current block's math (XLA schedules the overlap).

Layout: [batch, seq, heads, head_dim] with seq sharded over the
``sequence`` axis; inside the shard_map body every ref sees its local
sequence block.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import mesh as mesh_lib
from tensorflowonspark_tpu.utils import compat

NEG_INF = -1e30


def expand_heads(kv, num_heads: int):
  """Broadcast grouped-query KV heads up to the query head count (KV head
  j serves query heads [j*g, (j+1)*g) — blocked layout). Under GQA the
  ring permutes the UNEXPANDED blocks — a num_heads/kv_heads cut in ICI
  traffic. The flash path consumes them unexpanded too (the kernels'
  grouped-aware KV BlockSpec + cross-head dK/dV grid accumulation,
  ops.flash_attention module docstring — the round-3 ROADMAP deferral,
  closed); only the dense block math expands, and its einsum fuses the
  repeat. The ONE head-broadcast helper — models/transformer.py uses it
  too, so the grouping convention cannot drift."""
  hk = kv.shape[2]
  if hk == num_heads:
    return kv
  if num_heads % hk:
    raise ValueError("kv heads (%d) must divide query heads (%d)"
                     % (hk, num_heads))
  return jnp.repeat(kv, num_heads // hk, axis=2)


_expand_heads = expand_heads


def _block_attn(q, k, v, m, l, o, q_offset, kv_offset, causal, scale,
                window=None):
  """One online-softmax accumulation step against a single KV block.

  q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; m/l: [B, H, Sq]; o: [B, Sq, H, D].
  Positions are global offsets so causal masking works across shards.
  ``window``: sliding-window mask (last ``window`` positions, self
  included) — same convention as ops.flash_attention.
  """
  qf = q.astype(jnp.float32)
  kf = k.astype(jnp.float32)
  scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale  # [B,H,Sq,Sk]

  if causal:
    q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (q.shape[1], k.shape[1]), 0)
    k_pos = kv_offset + lax.broadcasted_iota(jnp.int32, (q.shape[1], k.shape[1]), 1)
    keep = k_pos <= q_pos
    if window is not None:
      keep = jnp.logical_and(keep, k_pos > q_pos - window)
    mask = keep[None, None]
    scores = jnp.where(mask, scores, NEG_INF)

  m_block = jnp.max(scores, axis=-1)                      # [B,H,Sq]
  m_new = jnp.maximum(m, m_block)
  # guard fully-masked rows (m_new == NEG_INF) against NaNs
  m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
  p = jnp.exp(scores - m_safe[..., None])
  p = jnp.where(scores <= NEG_INF, 0.0, p)
  correction = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - m_safe))
  l_new = l * correction + jnp.sum(p, axis=-1)
  pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
  o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
  return m_new, l_new, o_new


def _ring_attn_local(q, k, v, axis_name: str, causal: bool, window=None):
  """shard_map body: full attention with KV blocks rotating around the ring."""
  n = compat.jax_axis_size(axis_name)
  my = lax.axis_index(axis_name)
  b, s_local, h, d = q.shape
  scale = 1.0 / (d ** 0.5)

  m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
  l0 = jnp.zeros((b, h, s_local), jnp.float32)
  o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
  q_offset = my * s_local

  def body(step, carry):
    k_blk, v_blk, m, l, o = carry
    src = (my - step) % n                 # whose block we hold this step
    kv_offset = src * s_local
    m, l, o = _block_attn(q, _expand_heads(k_blk, h),
                          _expand_heads(v_blk, h), m, l, o, q_offset,
                          kv_offset, causal, scale, window)
    # rotate kv to the next neighbor (ICI ring); last rotation is unused but
    # keeps the loop shape static for XLA
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk = lax.ppermute(k_blk, axis_name, perm)
    v_blk = lax.ppermute(v_blk, axis_name, perm)
    return k_blk, v_blk, m, l, o

  _, _, m, l, o = lax.fori_loop(0, n, body, (k, v, m0, l0, o0))
  l = jnp.where(l == 0.0, 1.0, l)         # fully-masked rows -> zeros
  out = o / l.transpose(0, 2, 1)[..., None]
  return out.astype(q.dtype)


def _ring_flash_local(q, k, v, axis_name: str, causal: bool, blk_q: int,
                      blk_k: int, interpret: bool, blk_bwd_q=None,
                      blk_bwd_k=None, bwd=None, window=None):
  """shard_map body: ring attention with Pallas flash-attention blocks.

  Each ring step computes the partial attention of the local queries
  against the currently-held KV block with the fused kernel
  (ops.flash_attention_block) and merges the normalized partials via
  their logsumexps — the fused-kernel memory profile composed with
  sequence parallelism.
  """
  from tensorflowonspark_tpu.ops.flash_attention import (
      NEG_INF as _NEG_INF, flash_attention_block, merge_partials)

  n = compat.jax_axis_size(axis_name)
  my = lax.axis_index(axis_name)
  b, s_local, h, d = q.shape

  # accumulate the running output in float32 across ring steps (a bf16
  # carry would round n times); cast to the input dtype once at the end
  o0 = jnp.zeros(q.shape, jnp.float32)
  lse0 = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)

  def body(step, carry):
    k_blk, v_blk, o, lse = carry
    src = (my - step) % n
    # grouped KV feeds the kernel UNEXPANDED: the flash kernels carry a
    # grouped-aware KV BlockSpec (query head -> its KV head row) with
    # cross-head dK/dV accumulation in the backward grid, so the expanded
    # block never exists — not in HBM, not per step
    o_j, lse_j = flash_attention_block(
        q, k_blk, v_blk,
        my * s_local, src * s_local, causal=causal,
        blk_q=blk_q, blk_k=blk_k, interpret=interpret,
        blk_bwd_q=blk_bwd_q, blk_bwd_k=blk_bwd_k, bwd=bwd,
        window=window)
    o, lse = merge_partials(o, lse, o_j.astype(jnp.float32), lse_j)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk = lax.ppermute(k_blk, axis_name, perm)
    v_blk = lax.ppermute(v_blk, axis_name, perm)
    return k_blk, v_blk, o, lse

  _, _, o, _ = lax.fori_loop(0, n, body, (k, v, o0, lse0))
  return o.astype(q.dtype)


def ring_attention(q, k, v, mesh, causal: bool = True,
                   axis_name: str = mesh_lib.AXIS_SEQUENCE,
                   batch_axes=None, use_flash: bool = False,
                   blk_q: int = 256, blk_k: int = 512,
                   interpret: bool = False, blk_bwd_q: int = None,
                   blk_bwd_k: int = None, bwd: str = None,
                   window: int = None):
  """Exact full attention over a sequence sharded across ``axis_name``.

  Args:
    q, k, v: [batch, seq, heads, head_dim], seq sharded over ``axis_name``.
      K/V may carry FEWER heads than Q (grouped-query attention): the ring
      then permutes the small grouped blocks — ICI traffic drops by
      num_heads/kv_heads — and every step expands them locally before the
      block math. (If a tensor axis shards heads and cannot divide the
      grouped count, K/V are expanded up front instead.)
    mesh: the device mesh.
    causal: apply a global causal mask.
    batch_axes: mesh axes dim 0 is sharded over (defaults to data+fsdp).
    use_flash: compute each ring step's block with the fused Pallas kernel
      (ops.flash_attention_block) instead of dense block math — the
      memory-optimal path on TPU (``interpret=True`` for CPU tests).
      ``blk_q``/``blk_k`` tile the forward; ``blk_bwd_q``/``blk_bwd_k``
      tile the backward (None = per-mode DEFAULT_BWD_BLOCKS); ``bwd``
      picks the backward implementation per call ("fused"/"split",
      None = the TFOS_TPU_FLASH_BWD env default) — the same per-call
      override flash_attention itself offers.

  Returns attention output with the same sharding as ``q``.
  """
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map

  batch_axes = batch_axes if batch_axes is not None else \
      mesh_lib.data_axes(mesh)
  t = mesh_lib.axis_size(mesh, mesh_lib.AXIS_TENSOR)
  if k.shape[2] != q.shape[2] and k.shape[2] % max(1, t) != 0:
    # heads are tensor-sharded and the grouped count can't divide: expand
    # up front (the pre-GQA behavior) rather than break the head spec
    k = _expand_heads(k, q.shape[2])
    v = _expand_heads(v, q.shape[2])
  spec = P(batch_axes or None, axis_name, mesh_lib.AXIS_TENSOR
           if mesh_lib.AXIS_TENSOR in mesh.axis_names else None, None)
  if window is not None and not causal:
    raise ValueError("sliding-window ring attention requires causal=True")
  if use_flash:
    fn = functools.partial(_ring_flash_local, axis_name=axis_name,
                           causal=causal, blk_q=blk_q, blk_k=blk_k,
                           blk_bwd_q=blk_bwd_q, blk_bwd_k=blk_bwd_k, bwd=bwd,
                           interpret=interpret, window=window)
  else:
    fn = functools.partial(_ring_attn_local, axis_name=axis_name,
                           causal=causal, window=window)
  return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)(q, k, v)


def full_attention(q, k, v, causal: bool = True, window: int = None):
  """Single-device reference implementation (for tests and small models).
  ``window`` masks like the flash kernels' sliding window (each query sees
  its last ``window`` positions, self included) but materializes the
  dense mask — O(s²) memory, reference only."""
  if window is not None and not causal:
    raise ValueError("sliding-window attention requires causal=True "
                     "(same contract as ops.flash_attention)")
  b, s, h, d = q.shape
  scale = 1.0 / (d ** 0.5)
  scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale
  if causal:
    mask = jnp.tril(jnp.ones((s, s), bool))
    if window is not None:
      mask = jnp.logical_and(mask, ~jnp.tril(jnp.ones((s, s), bool),
                                             k=-window))
    scores = jnp.where(mask[None, None], scores, NEG_INF)
  probs = jax.nn.softmax(scores, axis=-1)
  out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
  return out.astype(q.dtype)
