"""L3' cluster lifecycle API: reserve → launch → (feed) → shutdown.

Capability parity with the reference's ``TFCluster.py``
(/root/reference/tensorflowonspark/TFCluster.py), generalized over the engine
abstraction (Spark or the built-in LocalEngine) and re-targeted at JAX/TPU:

- ``run()`` builds the role template mapping job names → executor ids
  (reference :256-271), starts the rendezvous server (:283-285), launches the
  node bring-up job asynchronously so feeding can proceed (:318-336), awaits
  and validates reservations with duplicate detection (:357-372);
- ``train()``/``inference()`` implement the engine-pushes-rows input mode,
  with epochs via dataset replication (parity with epochs-via-RDD.union,
  :90-94);
- ``shutdown()`` is PS-aware, pushes end-of-feed into worker queues via a
  shutdown job (:174-176), remotely stops ps/evaluator nodes through their
  driver-reachable hubs (:186-194), enforces a watchdog timeout (default 3
  days, :136-144) and raises if any node failed (:179-183).
"""

import collections.abc
import contextlib
import logging
import os
import random
import signal
import threading
import time
from typing import Dict, List, Optional, Sequence

from tensorflowonspark_tpu import node as node_mod
from tensorflowonspark_tpu.control import feedhub, rendezvous
from tensorflowonspark_tpu.engine.base import Engine, is_executor_lost
from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans

logger = logging.getLogger(__name__)


class ClusterSupervisor(object):
  """Driver-side node babysitter: detect dead nodes, relaunch, requeue.

  Two failure signals are watched:

  - **liveness**: executors whose heartbeats stopped past the missed-beat
    deadline (``rendezvous.Liveness`` — a SIGKILL, OOM kill, or TPU-pod
    preemption stops the beats without any traceback);
  - **engine**: node tasks that died WITH their executor (errors carrying
    the ``ExecutorLost`` marker from ``engine.base``).

  Application exceptions (a user fn raising) are NOT retried — they
  propagate exactly as without supervision; restarting a deterministic
  failure is futile and hides bugs. For restartable failures the recovery
  sequence is:

  1. back off (exponential with full jitter, capped at ``backoff_cap``;
     the attempt budget is ``max_restarts`` per executor);
  2. mark the dead node's hub ``dead`` and drain its undelivered feed
     rows (``datafeed.drain_pending_rows``) so blocked feeders complete
     and no delivered-but-unprocessed data is lost;
  3. relaunch the node task via ``Engine.relaunch_task``, handing the
     restart count to the new node (→ ``ctx.restart_count``; the user fn
     resumes via ``CheckpointManager.restore_or``);
  4. await re-registration, patch ``cluster_info`` in place (feed tasks
     submitted afterwards see the new hub), and refeed the drained rows
     through the engine feed path.

  Recoveries run serially on the supervisor thread — deterministic, and
  the backoff budget bounds total recovery time. ``wait_idle()`` lets
  callers (tests, pre-shutdown hooks) block until no recovery is active.
  """

  def __init__(self, engine: Engine, server: rendezvous.Server,
               node_job, cluster_meta: dict, cluster_info: List[dict],
               engine_ids: Sequence[int], tf_status: dict,
               max_restarts: int = 2, backoff: float = 0.5,
               backoff_cap: float = 5.0):
    self.engine = engine
    self.server = server
    self.node_job = node_job
    self.cluster_meta = cluster_meta
    self.cluster_info = cluster_info
    self.tf_status = tf_status
    self.max_restarts = max_restarts
    self.backoff = backoff
    self.backoff_cap = backoff_cap
    self._eid_task = {eid: i for i, eid in enumerate(engine_ids)}
    self._attempts: Dict[int, int] = {}
    self._given_up: set = set()
    #: executor_id -> completed restart count (observability)
    self.restarts: Dict[int, int] = {}
    #: recovery event log: dicts with executor_id / kind / t (monotonic)
    self.events: List[dict] = []
    self._stop = threading.Event()
    self._idle = threading.Event()
    self._idle.set()
    self._thread: Optional[threading.Thread] = None
    # obs seam: recovery events mirror into driver-side counters
    # (cluster.detected_dead / relaunched / recovered / gave_up /
    # skipped_background) and each recovery records a span
    self._obs_reg = obs_metrics.active()
    self._obs_rec = obs_spans.active()

  def _event(self, kind: str, **fields) -> None:
    # structured payloads (attempt / backoff_s / group / ...) mirror the
    # fleet's eject/failover events: obs_report --alerts post-mortems can
    # reconstruct a recovery or resize from the driver JSONL alone
    self.events.append(dict(fields, kind=kind, t=time.monotonic()))
    if self._obs_reg is not None:
      self._obs_reg.counter("cluster." + kind.replace("-", "_")).inc()
    if self._obs_rec is not None:
      self._obs_rec.event("cluster." + kind,
                          **{k: v for k, v in fields.items()
                             if isinstance(v, (int, float, str, bool))})

  def _group_of(self, eid: int):
    """The mesh group this executor hosts (cluster_meta ``group_map``),
    or None for ungrouped clusters. Keys tolerate str/int (the map may
    round-trip through JSON)."""
    gm = self.cluster_meta.get("group_map") or {}
    g = gm.get(eid, gm.get(str(eid)))
    return int(g) if g is not None else None

  # -- lifecycle -------------------------------------------------------------

  def start(self) -> "ClusterSupervisor":
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name="cluster-supervisor")
    self._thread.start()
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=30)

  def wait_idle(self, timeout: float = 60.0) -> bool:
    """Block until no recovery is in flight AND no failure is pending
    detection right now; True if idle within ``timeout``."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
      if self._idle.is_set() and not self._failed_executors():
        return True
      time.sleep(0.05)
    return False

  # -- detection -------------------------------------------------------------

  def _failed_executors(self) -> List[int]:
    if self.server.done.is_set():
      # the rendezvous server stopped serving (streaming stop / shutdown):
      # beats — including goodbyes — can no longer arrive, so silence is
      # not death; report nothing (mirrors the _loop stand-down, and keeps
      # wait_idle from stalling shutdown over phantom deaths)
      return []
    failed = set(self.server.liveness.dead())
    for task_id, err in enumerate(self.node_job.errors):
      if is_executor_lost(err):
        for eid, t in self._eid_task.items():
          if t == task_id:
            failed.add(eid)
    return sorted(e for e in failed
                  if e in self._eid_task and e not in self._given_up)

  def _loop(self) -> None:
    interval = self.cluster_meta.get("heartbeat_interval") or 5.0
    poll = max(0.05, min(1.0, interval / 4.0))
    while not self._stop.wait(poll):
      if self.server.done.is_set():
        # the rendezvous server stopped serving (streaming stop signal /
        # shutdown): heartbeats can no longer arrive, so silence is not
        # death — stand down instead of relaunching healthy nodes
        continue
      for eid in self._failed_executors():
        if self._stop.is_set():
          return
        self._idle.clear()
        try:
          if self._obs_rec is not None:
            with self._obs_rec.span("cluster.recover", executor_id=eid):
              self._recover(eid)
          else:
            self._recover(eid)
        except Exception:  # noqa: BLE001 - supervisor must survive anything
          logger.exception("recovery of executor %d failed", eid)
        finally:
          self._idle.set()

  # -- recovery --------------------------------------------------------------

  def _recover(self, eid: int) -> None:
    attempt = self._attempts.get(eid, 0)
    group = self._group_of(eid)
    self._event("detected-dead", executor_id=eid, attempt=attempt,
                group=group)
    try:
      job_name, _ = node_mod._role_of(eid, self.cluster_meta["cluster_template"])
    except ValueError:
      job_name = "worker"
    if job_name in node_mod.BACKGROUND_ROLES:
      # ps/evaluator bring-up tasks park on the hub control queue for the
      # cluster's whole life — a pinned relaunch could never schedule
      # behind the (healthy) foreground owner, and the replacement would
      # park on a fresh control queue shutdown never signals. Surface the
      # death instead of restarting (parity: the reference reported ps
      # failures at shutdown; supervised restart covers the JAX roles).
      self._given_up.add(eid)
      msg = ("%s node on executor %d died (background-role nodes are not "
             "relaunched; failure will surface at shutdown)"
             % (job_name, eid))
      logger.error(msg)
      self._event("skipped-background", executor_id=eid, group=group)
      if self.tf_status.get("error") is None:
        self.tf_status["error"] = msg
      return
    if attempt >= self.max_restarts:
      self._given_up.add(eid)
      if group is not None and self.cluster_meta.get("elastic"):
        # elastic mode: a grouped executor past its restart budget is a
        # RESIZE, not a job failure — commit the shrink on the sync plane
        # so surviving groups stop waiting for it (parallel.groups)
        self._commit_shrink(eid, group, attempt)
        return
      msg = ("executor %d declared dead after %d restart attempt(s); "
             "restart budget (max_restarts=%d) exhausted"
             % (eid, attempt, self.max_restarts))
      logger.error(msg)
      self._event("gave-up", executor_id=eid, attempts=attempt,
                  group=group)
      # the node task may have completed OK long ago (ENGINE mode: the
      # bring-up task returns before the background fn dies) — make sure
      # shutdown still raises
      if self.tf_status.get("error") is None:
        self.tf_status["error"] = msg
      return
    self._attempts[eid] = attempt + 1
    self.server.liveness.mark_restarting(eid)
    # exponential backoff with full jitter, hard-capped: no recovery-path
    # sleep ever exceeds backoff_cap
    delay = min(self.backoff_cap, self.backoff * (2 ** attempt))
    delay *= 0.5 + random.random()
    if self._stop.wait(min(delay, self.backoff_cap)):
      return

    old_meta = next((n for n in self.cluster_info
                     if n["executor_id"] == eid), None)
    pending = self._quarantine_dead_hub(old_meta)

    task_id = self._eid_task[eid]
    if not self.node_job._completed[task_id]:
      # the node task never finished — a hung user fn (liveness-dead but
      # process alive) would keep its executor busy forever and a pinned
      # relaunch could never schedule; kill the executor so the engine
      # fails the attempt and recycles the slot first
      if self.engine.preempt_task(self.node_job, task_id):
        deadline = time.monotonic() + 10
        while not self.node_job._completed[task_id] \
            and time.monotonic() < deadline and not self._stop.is_set():
          time.sleep(0.05)
    logger.warning("relaunching node on executor %d (attempt %d/%d, "
                   "%d feed row(s) requeued)", eid, attempt + 1,
                   self.max_restarts, sum(map(len, pending.values())))
    self.engine.relaunch_task(self.node_job, task_id,
                              payload={"executor_id": eid,
                                       "restart": attempt + 1})
    # re-arm the startup grace from the relaunch instant: a stale beat
    # from the OLD incarnation clears the restarting flag, and without a
    # fresh grace the next sweep would re-declare death mid-bring-up and
    # burn a second restart attempt on the same failure
    self.server.liveness.rearm(eid)
    self._event("relaunched", executor_id=eid, attempt=attempt + 1,
                backoff_s=round(delay, 3), group=group)

    reregistered = self._await_reregistration(eid, attempt + 1)
    if reregistered:
      self.restarts[eid] = attempt + 1
      self._event("recovered", executor_id=eid, attempt=attempt + 1,
                  group=group)
    else:
      # liveness/ExecutorLost will re-fire and consume another attempt,
      # or the task error (a non-restartable bring-up failure) propagates
      logger.warning("executor %d did not re-register after relaunch", eid)
    if pending:
      # refeed regardless of the relaunch outcome: the rescued rows go to
      # whichever LIVE worker picks up the feed task, so a slow relaunch
      # must not drop them
      self._refeed(pending)

  def _commit_shrink(self, eid: int, group: int, attempts: int) -> None:
    """Elastic resize, shrink direction: evict the dead executor's group
    from the sync plane so rounds never wait for it and its stale
    contributions are rejected; training continues on the survivors with
    the sync denominator reduced. Only an empty group set is fatal."""
    plane = getattr(self.server, "sync_plane", None)
    active = None
    if plane is not None:
      plane.mark_lost(group, "executor %d dead past restart budget "
                      "(%d attempt(s))" % (eid, attempts))
      active = plane.status()["groups_active"]
    logger.error("executor %d (group %d) declared dead after %d restart "
                 "attempt(s); committing the shrink — %s group(s) remain",
                 eid, group, attempts, active)
    self._event("resize-shrink", executor_id=eid, group=group,
                attempts=attempts, groups_active=active)
    if active == 0 and self.tf_status.get("error") is None:
      self.tf_status["error"] = (
          "all training groups lost (last: group %d on executor %d)"
          % (group, eid))

  def readmit(self, eid: int) -> None:
    """Elastic resize, grow/re-admit direction: the engine brought the
    executor's capacity back (or an operator re-added it) after the
    supervisor gave up on it. The restart budget resets and liveness
    re-arms its startup grace so the rebooting node isn't re-declared
    dead mid-bring-up; the node's group rejoins the sync plane itself
    (``GroupSyncClient.join`` pulls the catch-up weights) at its next
    sync boundary."""
    self._given_up.discard(eid)
    self._attempts.pop(eid, None)
    self.server.liveness.rearm(eid)
    self._event("resize-readmit", executor_id=eid,
                group=self._group_of(eid))

  def _quarantine_dead_hub(self, old_meta: Optional[dict]) -> Dict[str, List]:
    """Mark the dead node's hub unusable and rescue undelivered feed rows.

    The hub manager is a separate process and routinely survives its
    node's death; marking it ``dead`` makes the relaunched node's reclaim
    check (node.py) treat it as stale, and the drain releases feeders
    blocked on ``queue.join``. Best-effort: an unreachable hub (true for
    remote workers' loopback hubs) just means nothing to rescue.
    """
    if old_meta is None:
      return {}
    try:
      hub = feedhub.connect(tuple(old_meta["hub_addr"]),
                            self.cluster_meta["authkey"])
      hub.set("state", "dead")
    except Exception:  # noqa: BLE001 - hub died with the node
      return {}
    pending: Dict[str, List] = {}
    if self.cluster_meta.get("input_mode") == InputMode.ENGINE:
      from tensorflowonspark_tpu.datafeed import drain_pending_rows
      # inference feeds need their EndPartition markers preserved in
      # stream order across the refeed, or per-partition result alignment
      # is lost (TPUCluster.inference stamps feed_kind on the shared meta)
      keep_markers = self.cluster_meta.get("feed_kind") == "inference"
      # every DATA queue, not just the default: train/inference accept a
      # custom qname and those rows (and their blocked feeders) need the
      # drain just as much
      for qname in self.cluster_meta.get("queues", ("input",)):
        if qname in ("error", "output", "control"):
          continue
        try:
          rows = drain_pending_rows(hub, qname, keep_markers=keep_markers)
        except Exception:  # noqa: BLE001 - manager vanished mid-drain
          logger.warning("draining queue %r of executor %d's dead hub "
                         "failed", qname, old_meta["executor_id"])
          continue
        if rows:
          pending[qname] = rows
    return pending

  def _await_reregistration(self, eid: int, generation: int,
                            timeout: float = 120.0) -> bool:
    """Poll the reservation table until the relaunched node registered its
    restart ``generation``; patch cluster_info in place on success. (The
    pid alone can't identify the new incarnation: an ENGINE-mode relaunch
    runs in the same executor process as its predecessor.)"""
    deadline = time.monotonic() + min(
        timeout, self.cluster_meta.get("reservation_timeout", timeout))
    while time.monotonic() < deadline and not self._stop.is_set():
      for n in self.server.reservations.get():
        if n["executor_id"] == eid and n.get("restart") == generation:
          for meta in self.cluster_info:
            if meta["executor_id"] == eid:
              meta.update(n)
          return True
      # a relaunch that failed bring-up for an application reason (not an
      # executor loss) will never register — stop waiting and let the
      # task error propagate
      err = self.node_job.errors[self._eid_task[eid]]
      if err is not None and not is_executor_lost(err):
        return False
      time.sleep(0.05)
    return False

  def _refeed(self, pending: Dict[str, List]) -> None:
    """Requeue rescued feed rows through the engine feed path — one feed
    task per drained queue, back into the SAME qname: they land on
    whichever live worker picks the task up (at-least-once delivery for
    rows the dead worker never processed)."""
    for qname, rows in pending.items():
      fn = node_mod.make_train_fn(self.cluster_info, self.cluster_meta,
                                  qname=qname)
      try:
        self.engine.foreach_partition([rows], fn).wait(timeout=120)
        logger.info("requeued %d feed row(s) into %r from the dead node",
                    len(rows), qname)
      except Exception as e:  # noqa: BLE001 - best-effort; loss is logged
        logger.error("requeueing %d rescued feed row(s) into %r failed: %s",
                     len(rows), qname, e)


def _driver_obs_log(recorder=None):
  """The driver's per-process obs JSONL (anchored by the recorder's
  clock when one is live) — shared between the detector's per-alert
  appends and the shutdown span/metrics dump."""
  from tensorflowonspark_tpu.obs import export as obs_export
  return obs_export.ProcessLog(
      label="driver", executor_id=0,
      clock=recorder.clock if recorder is not None else None)


class InputMode(object):
  """How the cluster gets training data (parity: TFCluster.py:43-46).

  ``FILES`` (alias ``TENSORFLOW``): each node reads its own data shard
  (grain / tf.data / raw files from GCS or local disk); the engine only holds
  the executor slots.

  ``ENGINE`` (alias ``SPARK``): the engine pushes partitioned rows into each
  node's feed hub, consumed by the user fn through a DataFeed.
  """
  FILES = 0
  TENSORFLOW = 0
  ENGINE = 1
  SPARK = 1


class _StreamFeedHandle(object):
  """Progress of a hooked (D)Stream feed: micro-batches fed + stop flag."""

  def __init__(self):
    self.rounds = 0
    self.stopped = False


class TPUCluster(object):
  """Handle for a started cluster (parity: TFCluster.py:49-212)."""

  def __init__(self, engine: Engine, cluster_info: List[dict],
               cluster_meta: dict, server: rendezvous.Server,
               input_mode: int, node_job, tf_status: dict,
               driver_ps_procs: Sequence = (), supervisor=None,
               detector=None):
    self.engine = engine
    self.cluster_info = cluster_info
    self.cluster_meta = cluster_meta
    self.server = server
    self.input_mode = input_mode
    self.node_job = node_job
    self.tf_status = tf_status
    self.queues = cluster_meta["queues"]
    self.driver_ps_procs = list(driver_ps_procs)
    self.supervisor = supervisor
    #: the driver-side obs aggregation (obs.collector.ObsSink) when the
    #: obs plane is on (TOS_OBS=1) — executors ship metric/span deltas
    #: here through the rendezvous OBS verb; None when off. getattr:
    #: tests (and embedders) hand in stand-in servers without the field
    self.obs_sink = getattr(server, "obs_sink", None)
    #: the driver-side detector loop (obs.anomaly.AnomalyDetector)
    #: evaluating the sink online; None when the plane (or the detector,
    #: TOS_OBS_DETECT=0) is off
    self.detector = detector

  def alerts(self, max_items: int = 64) -> List[dict]:
    """Newest-first structured alerts from the online detector loop
    (empty when the obs plane / detector is off)."""
    if self.detector is None:
      return []
    return self.detector.recent_alerts(max_items)

  def obs_summary(self) -> dict:
    """The in-process equivalent of the HEALTH verb's obs payload:
    liveness snapshot + per-executor metric state + live alerts + SLO
    status — the driver summary ``tools/obs_top.py`` renders when
    embedded."""
    out = {"data": {str(k): v for k, v in
                    self.server.liveness.snapshot().items()}}
    if self.obs_sink is not None:
      out["obs"] = self.obs_sink.top_summary()
    if self.detector is not None:
      out["alerts"] = self.detector.recent_alerts()
      slo = self.detector.slo_status()
      if slo is not None:
        out["slo"] = slo
      dep = self.detector.deploy_status()
      if dep is not None:
        out["deploy"] = dep
    return out

  def slo_status(self) -> Optional[dict]:
    """Live SLO burn-rate verdicts (``obs.slo``; None when the obs
    plane/detector is off or no objectives are declared) — the
    driver-side read the train→serve canary phase consumes."""
    if self.detector is None:
      return None
    return self.detector.slo_status()

  def deploy_status(self) -> Optional[dict]:
    """Live continuous-deployment state (``serving.deploy`` gauges as
    sampled by the detector; None when the obs plane/detector is off or
    no controller has shipped ``deploy.*`` yet) — which version serves,
    which candidate is canarying, how many rollbacks."""
    if self.detector is None:
      return None
    return self.detector.deploy_status()

  @staticmethod
  def _span(name: str, **attrs):
    """Driver-side span, or a null context when the obs plane is off."""
    rec = obs_spans.active()
    if rec is None:
      return contextlib.nullcontext()
    return rec.span(name, **attrs)

  # -- data plane ------------------------------------------------------------

  def train(self, data_partitions: Sequence, num_epochs: int = 0,
            feed_timeout: float = 600, qname: str = "input"):
    """Feed partitioned data to the cluster (ENGINE input mode only).

    Epochs are implemented by replicating the dataset ``num_epochs`` times
    (parity with epochs-via-RDD.union, reference TFCluster.py:90-94).
    Returns None for bounded data; a DStream argument returns the stream
    feed handle from :meth:`train_dstream`.
    """
    if hasattr(data_partitions, "foreachRDD"):
      # a Spark DStream handed straight to train(), exactly as the
      # reference accepted (TFCluster.py:83-85); the handle exposes
      # rounds-fed / stop-observed progress
      return self.train_dstream(data_partitions, feed_timeout=feed_timeout,
                                qname=qname)
    logger.info("feeding training data")
    assert self.input_mode == InputMode.ENGINE, \
        "train() requires InputMode.ENGINE/SPARK"
    self.cluster_meta["feed_kind"] = "train"
    epochs = max(1, num_epochs)
    parts = self._wrap_lazy(data_partitions)
    fn = node_mod.make_train_fn(self.cluster_info, self.cluster_meta,
                                feed_timeout=feed_timeout, qname=qname)
    if isinstance(parts, collections.abc.Iterator):
      # one-shot partition streams cannot be replayed (and _replicate's
      # fallback would drain the generator eagerly on the driver, feeding
      # epoch 1 and silently starving epochs 2..N), so route them through
      # the engine's lazy path. On LocalEngine the driver holds one window
      # of partitions in flight, never the whole dataset; SparkEngine's
      # _as_rdd still drains the stream into a driver-side list of
      # partition HANDLES before parallelize — O(dataset) only if the
      # stream carries raw rows instead of callables (use lazy handles or
      # train_dstream for big data on Spark)
      if epochs > 1:
        raise ValueError(
            "train(num_epochs=%d) got a one-shot partition iterator; "
            "re-iterable input (a list, an RDD, or lazy handles) is "
            "required to replay epochs" % epochs)
      stream = self.engine.map_partitions_lazy(parts, fn,
                                               timeout=feed_timeout)
      if isinstance(stream, collections.abc.Iterator):
        for _ in stream:   # windowed: one window in flight on the driver
          pass
      else:
        # RDD-like lazy result (SparkEngine hands back an uncollected
        # RDD): trigger the feed with a row-free action — count() runs
        # the tasks distributed and returns only a number
        stream.count()
      return
    parts = self._replicate(parts, epochs)
    with self._span("cluster.train_feed", epochs=epochs):
      self.engine.foreach_partition(parts, fn).wait()

  def train_stream(self, batch_stream, feed_timeout: float = 600,
                   qname: str = "input") -> int:
    """Feed an unbounded stream of partitioned datasets (micro-batches).

    The analog of the reference's Spark Streaming support
    (DStream.foreachRDD feeding, TFCluster.py:83-85): each item of
    ``batch_stream`` is a list of partitions fed as one round. A graceful
    stop request (``request_stop()``, or a remote
    ``rendezvous.Client(addr).request_stop()`` — parity with
    examples/utils/stop_streaming.py) ends the loop after the current
    round. Returns the number of rounds fed.
    """
    assert self.input_mode == InputMode.ENGINE, \
        "train_stream() requires InputMode.ENGINE/SPARK"
    rounds = 0
    for partitions in batch_stream:
      # feed first, check after: a batch already pulled from the source is
      # never discarded (sources may commit offsets on yield)
      self.train(partitions, num_epochs=1, feed_timeout=feed_timeout,
                 qname=qname)
      rounds += 1
      if self.server.stopping():
        logger.info("stop signal received; ending stream after %d rounds",
                    rounds)
        break
    return rounds

  def train_dstream(self, dstream, feed_timeout: float = 600,
                    qname: str = "input"):
    """Hook a Spark (D)Stream so every micro-batch RDD is fed as one round
    (parity: reference TFCluster.train wiring ``dataRDD.foreachRDD(_train)``,
    TFCluster.py:83-85).

    Feeding happens on Spark's streaming driver thread as batches arrive.
    After a graceful stop request (``request_stop()``, or a remote
    ``rendezvous.Client(addr).request_stop()`` — parity with
    examples/utils/stop_streaming.py) later micro-batches are skipped
    without being consumed, so the streaming job can be stopped and
    ``shutdown()`` called. Returns a handle whose ``rounds`` attribute
    counts the micro-batches fed so far and whose ``stopped`` flag reports
    whether the stop signal has been observed.
    """
    assert self.input_mode == InputMode.ENGINE, \
        "train_dstream() requires InputMode.ENGINE/SPARK"
    self.cluster_meta["feed_kind"] = "train"
    fn = node_mod.make_train_fn(self.cluster_info, self.cluster_meta,
                                feed_timeout=feed_timeout, qname=qname)
    handle = _StreamFeedHandle()

    def _feed(rdd):
      if self.server.stopping():
        if not handle.stopped:
          logger.info("stop signal received; skipping further micro-batches "
                      "after %d rounds", handle.rounds)
        handle.stopped = True
        return
      self.engine.foreach_partition(rdd, fn).wait()
      handle.rounds += 1

    dstream.foreachRDD(_feed)
    return handle

  def foreach_batch(self, feed_timeout: float = 600, qname: str = "input"):
    """A ``(batch_df, batch_id) -> None`` callback for Structured Streaming:
    ``query = df.writeStream.foreachBatch(cluster.foreach_batch()).start()``.

    The modern equivalent of the DStream hook above: each micro-batch
    DataFrame is fed as one round; after a stop request batches are
    skipped. The reference predates Structured Streaming — this is the
    same capability on the current Spark API.
    """
    assert self.input_mode == InputMode.ENGINE, \
        "foreach_batch() requires InputMode.ENGINE/SPARK"
    self.cluster_meta["feed_kind"] = "train"
    fn = node_mod.make_train_fn(self.cluster_info, self.cluster_meta,
                                feed_timeout=feed_timeout, qname=qname)

    def _feed(batch_df, batch_id):
      if self.server.stopping():
        return
      self.engine.foreach_partition(batch_df, fn).wait()

    return _feed

  def request_stop(self) -> None:
    """Signal streaming feeds to stop after the current round.

    Sets the server's stop-REQUESTED flag only: the rendezvous keeps
    serving (bring-up polls, heartbeats, goodbyes) until ``shutdown()``
    actually stops it."""
    self.server.stop_requested.set()

  @property
  def server_addr(self):
    """Rendezvous address — remote processes can send the streaming stop
    signal here via ``rendezvous.Client(addr).request_stop()``."""
    return self.server.addr

  def inference(self, data_partitions: Sequence, feed_timeout: float = 600,
                qname: str = "input", collect: bool = True):
    """Feed data for inference (parity: TFCluster.inference, reference
    TFCluster.py:96-115).

    With ``collect=True`` (default) results are gathered into a driver-side
    list — fine for small jobs. With ``collect=False`` the return value is
    the engine's lazy handle (Spark: the uncollected result RDD, exactly
    like the reference; LocalEngine: a streaming generator holding at most
    one window of partitions), so cluster-scale inference output never
    materializes on the driver.
    """
    logger.info("feeding inference data")
    assert self.input_mode == InputMode.ENGINE, \
        "inference() requires InputMode.ENGINE/SPARK"
    # recovery drains must keep EndPartition markers for inference feeds
    # (ClusterSupervisor._quarantine_dead_hub reads this off the shared meta)
    self.cluster_meta["feed_kind"] = "inference"
    fn = node_mod.make_inference_fn(self.cluster_info, self.cluster_meta,
                                    feed_timeout=feed_timeout, qname=qname)
    data_partitions = self._wrap_lazy(data_partitions)
    if collect:
      with self._span("cluster.inference_feed"):
        return self.engine.map_partitions(data_partitions, fn)
    return self.engine.map_partitions_lazy(data_partitions, fn,
                                           timeout=feed_timeout)

  # -- lifecycle -------------------------------------------------------------

  def shutdown(self, grace_secs: float = 0, timeout: int = 259200) -> None:
    """Stop the cluster; raise if any node failed.

    ``timeout`` arms a SIGALRM watchdog (3-day default) guarding against
    hung shutdowns (parity: TFCluster.py:117,136-144).
    """
    in_main = threading.current_thread() is threading.main_thread()
    if timeout and in_main:
      def _watchdog(signum, frame):
        raise TimeoutError("cluster shutdown watchdog fired after %ds" % timeout)
      old = signal.signal(signal.SIGALRM, _watchdog)
      signal.alarm(int(timeout))
    try:
      with self._span("cluster.shutdown"):
        self._shutdown_inner(grace_secs)
    finally:
      if timeout and in_main:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
      # offline-log plane: the driver's own spans + metrics land in the
      # same per-process JSONL scheme the executors use, so
      # tools/obs_report.py merges one run from one directory
      self._dump_driver_obs_log()

  def _dump_driver_obs_log(self) -> None:
    if not obs_metrics.enabled():
      return
    rec = obs_spans.active()
    reg = obs_metrics.active()
    # reuse the detector's log when one exists: same file, ONE meta header
    log = self.detector.jsonl if self.detector is not None \
        and self.detector.jsonl is not None else _driver_obs_log(rec)
    if rec is not None:
      log.append_spans(rec.drain(None))
    log.close(metrics_snapshot=reg.snapshot() if reg is not None else None)

  def _shutdown_inner(self, grace_secs: float) -> None:
    workers = [n for n in self.cluster_info
               if n["job_name"] in node_mod.JAX_ROLES]
    background = [n for n in self.cluster_info
                  if n["job_name"] in node_mod.BACKGROUND_ROLES]

    if self.input_mode == InputMode.ENGINE:
      # push end-of-feed markers through a shutdown job on free (worker)
      # executors (parity: TFCluster.py:174-176)
      fn = node_mod.make_shutdown_fn(
          self.cluster_info, self.cluster_meta, grace_secs=grace_secs,
          queues=[q for q in self.queues if q not in ("error", "output",
                                                      "control")])
      self.engine.foreach_partition([[n["executor_id"]] for n in workers],
                                    fn).wait()
    elif any(n.get("tb_url") for n in self.cluster_info):
      # FILES mode has no feed-shutdown job; still reap the TensorBoard the
      # chief spawned. One PINNED task per executor slot (shared-queue tasks
      # could all land on one free executor and miss the chief's), each
      # best-effort so a dead node can't abort the rest of shutdown.
      fn = node_mod.make_tb_kill_fn(self.cluster_info, self.cluster_meta)
      try:
        self.engine.run_on_executors(
            fn, num_tasks=self.engine.num_executors).wait(
                raise_on_error=False)
      except Exception as e:  # noqa: BLE001 - reap is best-effort
        logger.warning("tensorboard reap job failed: %s", e)

    # stop ps/evaluator nodes by reaching their remote hubs directly
    # (parity: TFCluster.py:186-194)
    for n in background:
      try:
        hub = feedhub.connect(tuple(n["hub_addr"]),
                              self.cluster_meta["authkey"])
        hub.get_queue("control").put(None, block=True, timeout=30)
      except Exception as e:  # noqa: BLE001 - best-effort stop of sidecars
        logger.warning("failed to stop %s:%d: %s", n["job_name"],
                       n["task_index"], e)

    # driver-hosted ps processes exit once their control queue gets None
    for p in self.driver_ps_procs:
      p.join(timeout=60)
      if p.is_alive():
        logger.warning("driver ps process %s did not exit; terminating",
                       p.name)
        p.terminate()

    # wait for the node bring-up job itself (foreground workers return when
    # the user fn finishes); propagate node errors. The supervisor stays
    # live until the job settles: a node death racing shutdown un-completes
    # the job while its recovery runs, so drain recoveries (budget-bounded)
    # and re-wait until the job is stably done, THEN stand the supervisor
    # down before errors are read.
    self.node_job.wait(raise_on_error=False)
    if self.supervisor is not None:
      while True:
        settled = self.supervisor.wait_idle(timeout=120)
        if not settled:
          # a recovery is still in flight after the drain budget: stopping
          # the supervisor now interrupts it (the restarted task's error
          # slot was cleared), so record the situation rather than letting
          # shutdown report success over an unrecovered death
          if self.tf_status.get("error") is None:
            self.tf_status["error"] = (
                "shutdown proceeded while a node recovery was still in "
                "flight (supervisor busy past the drain budget)")
          break
        if self.node_job.done():
          break
        self.node_job.wait(raise_on_error=False)
      self.supervisor.stop()
    if self.detector is not None:
      # stand the loop down FIRST (stop joins the thread), then one last
      # pass so late-arriving deltas (executors final-flush on exit) are
      # evaluated — the other order races the thread's own poll
      self.detector.stop()
      self.detector.poll()
    self.server.stop()
    err = self.node_job.first_error() or self.tf_status.get("error")
    if err:
      raise RuntimeError("cluster shutdown with node error:\n%s" % err)
    logger.info("cluster shutdown complete")

  def tensorboard_url(self) -> Optional[str]:
    """URL of the TensorBoard server, if one was launched (parity:
    TFCluster.tensorboard_url, TFCluster.py:207-212)."""
    for n in self.cluster_info:
      if n.get("tb_url"):
        return n["tb_url"]
    return None

  @staticmethod
  def _wrap_lazy(parts):
    """Bare-callable partitions (lazy handles, e.g. from
    ``load_tfrecords(lazy=True)``) become single-item partitions the
    feeders resolve executor-side (node._materialize_partition).
    Engine-native handles and row partitions pass through untouched."""
    if hasattr(parts, "mapPartitions") or hasattr(parts, "rdd") \
        or hasattr(parts, "foreachRDD"):
      return parts
    if isinstance(parts, collections.abc.Iterator):
      # a one-shot stream of partitions (the collect=False windowed path)
      # must stay a stream — the driver pulls one window at a time
      return ([p] if callable(p) else p for p in parts)
    # any re-iterable collection wraps eagerly (epoch replication
    # re-iterates it)
    return [[p] if callable(p) else p for p in parts]

  @staticmethod
  def _replicate(parts: Sequence, epochs: int):
    """Repeat the dataset ``epochs`` times without touching its rows.

    Engine-native handles (an RDD, or a DataFrame wrapping one) replicate
    via ``union`` — the reference's epochs idiom (``sc.union([rdd]*N)``,
    TFCluster.py:90-94) — so the driver never iterates cluster data.
    Driver-side partition lists are simply concatenated.
    """
    if hasattr(parts, "rdd"):           # DataFrame → its RDD
      parts = parts.rdd
    if hasattr(parts, "mapPartitions"):  # RDD-like: epochs via union
      out = parts
      for _ in range(epochs - 1):
        out = out.union(parts)
      return out
    out = []
    for _ in range(epochs):
      out.extend(parts)
    return out


def run(engine: Engine, main_fn, tf_args=None,
        num_executors: Optional[int] = None, num_ps: int = 0,
        tensorboard: bool = False, input_mode: int = InputMode.FILES,
        log_dir: Optional[str] = None, driver_ps_nodes: bool = False,
        master_node: Optional[str] = None,
        reservation_timeout: float = 600,
        queues: Sequence[str] = ("input", "output", "error", "control"),
        eval_node: bool = False, release_port: bool = True,
        chips_per_node: int = 0, qmax: int = 1024,
        feed_transport: str = "auto", feed_chunk_size: int = 256,
        shm_capacity: int = 64 * 1024 * 1024,
        heartbeat_interval: Optional[float] = 5.0,
        supervise: bool = True, max_restarts: int = 2,
        restart_backoff: float = 0.5,
        restart_backoff_cap: float = 5.0,
        train_unroll: Optional[int] = None,
        group_map: Optional[Dict[int, int]] = None,
        elastic: bool = False,
        feed_segment=None,
        feed_target_bytes: Optional[int] = None) -> TPUCluster:
  """Start a cluster and run ``main_fn(tf_args, ctx)`` on every node.

  Signature parity with the reference's ``TFCluster.run``
  (TFCluster.py:215-245), with the engine abstraction in place of a
  SparkContext and TPU chip allocation in place of GPU counts.
  ``driver_ps_nodes`` hosts the ps nodes on the driver machine so every
  engine executor keeps its accelerator for workers (parity :229,298-316;
  FILES input mode only, like the reference).

  Fault tolerance: every node heartbeats the rendezvous server every
  ``heartbeat_interval`` seconds (None disables); a node silent for 2
  intervals is declared dead. With ``supervise=True`` a driver-side
  :class:`ClusterSupervisor` relaunches dead nodes (executor killed,
  preempted, OOM — NOT application exceptions, which propagate as
  always) up to ``max_restarts`` times per executor, with exponential
  backoff between ``restart_backoff`` and ``restart_backoff_cap``
  seconds. Relaunched nodes see ``ctx.restart_count > 0`` and should
  resume via ``ctx.checkpoint_manager(d).restore_or(state)``.

  ``train_unroll=K`` exports ``TOS_TRAIN_UNROLL=K`` into every node so
  ``parallel.sharding.make_train_loop`` / ``data.readers.slab_batches``
  default to fusing K optimizer steps per dispatch (1/None = the
  per-step status quo; see docs/PERFORMANCE.md §Train-loop fusion).

  ``group_map={executor_id: group_id}`` declares elastic multi-group
  training topology (``parallel.groups``): the rendezvous server grows a
  :class:`~parallel.groups.SyncPlane` (SYNC/SYNCQ/GROUP verbs + HEALTH
  ``groups`` telemetry) and supervisor events carry the group. With
  ``elastic=True`` a grouped executor that exhausts its restart budget
  COMMITS A SHRINK — surviving groups keep stepping with the sync
  denominator reduced — instead of failing the job; only losing every
  group is fatal. ``ClusterSupervisor.readmit`` re-opens the budget when
  capacity returns (docs/ROBUSTNESS.md §Elastic training).

  ``feed_segment`` (a ``data.datapipe.FeederSegment`` from
  ``Dataset.split_pushdown()``) runs the graph's pushable map/filter
  prefix inside every feeder task BEFORE the wire codec — filtered rows
  never ship, projecting maps shrink columns on the wire; the consumer
  side runs the remainder graph. ``feed_target_bytes`` sets the feeders'
  adaptive per-envelope byte budget (see ``node.ENV_FEED_TARGET_BYTES``;
  None/0 keeps the fixed ``feed_chunk_size`` row count). See
  docs/PERFORMANCE.md §Wire efficiency.
  """
  num_executors = num_executors or engine.num_executors
  if train_unroll is not None and int(train_unroll) < 1:
    raise ValueError("train_unroll must be >= 1, got %r" % (train_unroll,))
  if feed_target_bytes is not None and int(feed_target_bytes) < 0:
    raise ValueError("feed_target_bytes must be >= 0, got %r"
                     % (feed_target_bytes,))
  if feed_transport == "auto":
    # shared-memory rings require the feeder task and the node to share a
    # host, which only engines with colocated executors guarantee; the
    # node itself still falls back to "queue" if the native ring is absent
    feed_transport = "shm" if getattr(engine, "colocated_executors", False) \
        else "queue"
  if driver_ps_nodes and input_mode != InputMode.FILES:
    raise ValueError("driver_ps_nodes requires InputMode.FILES/TENSORFLOW "
                     "(parity with the reference)")
  engine_nodes = num_executors - (num_ps if driver_ps_nodes else 0)
  if engine_nodes > engine.num_executors:
    raise ValueError("cluster of %d nodes needs %d executors but engine has %d"
                     % (num_executors, engine_nodes, engine.num_executors))

  # role template (parity: TFCluster.py:256-271): ps nodes first, then
  # master/chief, evaluator, workers
  num_master = 1 if master_node else 0
  num_eval = 1 if eval_node else 0
  num_workers = max(num_executors - num_ps - num_eval - num_master, 0)
  total = num_ps + num_master + num_eval + num_workers
  assert total == num_executors, \
      "cluster requires %d nodes but %d executors reserved" % (total,
                                                               num_executors)
  assert num_master + num_workers > 0, \
      "cluster requires at least one worker or master/chief node"
  if num_ps > 0:
    logger.warning(
        "num_ps=%d: parameter servers are API-compatible but architecturally "
        "obsolete on TPU — synchronous data parallelism over ICI is the "
        "native strategy; ps nodes will run as background sidecars", num_ps)

  executors = list(range(num_executors))
  cluster_template: Dict[str, List[int]] = {}
  idx = 0
  if num_ps:
    cluster_template["ps"] = executors[idx:idx + num_ps]
    idx += num_ps
  if num_master:
    cluster_template[master_node] = executors[idx:idx + 1]
    idx += 1
  if num_eval:
    cluster_template["evaluator"] = executors[idx:idx + 1]
    idx += 1
  if num_workers:
    cluster_template["worker"] = executors[idx:]
  logger.info("cluster template: %s", cluster_template)

  # startup grace = the reservation window: a node is allowed to sit
  # between REG and its first own beat for as long as cluster assembly may
  # legitimately take (executor deaths in that window are still caught by
  # the engine's ExecutorLost signal)
  server = rendezvous.Server(num_executors,
                             heartbeat_interval=heartbeat_interval,
                             startup_grace=reservation_timeout)
  if obs_metrics.enabled():
    # the driver end of the obs plane: executors ship metric/span deltas
    # through the rendezvous OBS verb into this bounded sink
    from tensorflowonspark_tpu.obs import collector as obs_collector
    from tensorflowonspark_tpu.obs import device as obs_device
    server.obs_sink = obs_collector.ObsSink()
    # compile/device tier, driver side: the driver jits too (sharded
    # init, serving warm-up) and its compiles belong on the timeline
    obs_device.install(None)
  if group_map or elastic:
    # the driver end of the elastic-training plane: groups exchange
    # weights through the SYNC verbs, HEALTH replies carry the topology
    from tensorflowonspark_tpu.parallel import groups as groups_mod
    groups_mod.attach_sync_plane(server)
  server_addr = server.start()

  cluster_meta = {
      "id": random.getrandbits(64),
      "cluster_template": cluster_template,
      "num_executors": num_executors,
      "server_addr": list(server_addr),
      "authkey": os.urandom(16),
      "queues": list(queues),
      "input_mode": input_mode,
      "default_fs": engine.default_fs(),
      "reservation_timeout": reservation_timeout,
      "tensorboard": tensorboard,
      "log_dir": log_dir,
      "release_port": release_port,
      "chips_per_node": chips_per_node,
      "qmax": qmax,
      # "queue" (manager-proxy, works everywhere) or "shm" (native
      # shared-memory ring for the input stream; single host or per-host).
      # The default "auto" resolved above: shm on colocated engines.
      "feed_transport": feed_transport,
      # rows per feed chunk: one codec envelope / ring payload per chunk —
      # the transport batching unit AND the columnar assembly granularity
      "feed_chunk_size": feed_chunk_size,
      "shm_capacity": max(shm_capacity, 8 * 1024 * 1024),
      "heartbeat_interval": heartbeat_interval,
      # fused train loop default: every node exports this as
      # TOS_TRAIN_UNROLL (node._apply_node_env) so make_train_loop /
      # slab_batches resolve the cluster's K without per-fn plumbing
      "train_unroll": int(train_unroll) if train_unroll else None,
      # elastic multi-group training (parallel.groups): executor -> mesh
      # group id, and whether a group past its restart budget shrinks the
      # group set (resize) instead of failing the job
      "group_map": ({int(k): int(v) for k, v in group_map.items()}
                    if group_map else None),
      "elastic": bool(elastic),
      # wire-efficient feed plane (docs/PERFORMANCE.md §Wire efficiency):
      # the pushdown segment feeder tasks run before the codec, and the
      # adaptive per-envelope byte budget (None/0 = fixed row count)
      "feed_segment": feed_segment,
      "feed_target_bytes": (int(feed_target_bytes)
                            if feed_target_bytes else None),
  }

  # launch node bring-up asynchronously so that (a) feeding can start and
  # (b) reservation failures surface through tf_status (parity :318-336)
  tf_status: Dict[str, Optional[str]] = {"error": None}
  node_fn = node_mod.make_node_fn(main_fn, tf_args, cluster_meta)

  driver_ps_procs = []
  if driver_ps_nodes and num_ps:
    # ps nodes run on the driver machine in their own processes/workdirs
    import cloudpickle
    import multiprocessing as mp
    import tempfile
    mapfn_bytes = cloudpickle.dumps(node_fn)
    ctx_mp = mp.get_context("spawn")
    for ps_id in cluster_template["ps"]:
      wd = tempfile.mkdtemp(prefix="tos_driver_ps_%d_" % ps_id)
      p = ctx_mp.Process(target=node_mod.driver_node_main,
                         args=(mapfn_bytes, ps_id, wd),
                         name="driver-ps-%d" % ps_id)
      p.start()
      driver_ps_procs.append(p)
    engine_ids = [i for i in executors if i not in cluster_template["ps"]]
  else:
    engine_ids = executors

  node_job = engine.run_on_executors(node_fn, num_tasks=len(engine_ids),
                                     task_payloads=engine_ids)

  def _watch_job():
    # poll: a single failed bring-up task must surface its traceback
    # immediately (aborting await_reservations), not after the surviving
    # tasks run out their reservation timeout; driver-hosted ps processes
    # get the same treatment (a crashed child has a nonzero exitcode).
    # Executor-death errors (the ExecutorLost marker) belong to the
    # supervisor when one is running — it relaunches instead of aborting,
    # and sets tf_status itself when the restart budget runs out.
    while not node_job.done():
      err = node_job.first_error()
      if supervise and is_executor_lost(err):
        err = None
      for p in driver_ps_procs:
        if p.exitcode not in (None, 0):
          err = err or ("driver ps process %s exited with code %s during "
                        "bring-up" % (p.name, p.exitcode))
      if err:
        tf_status["error"] = err
        return
      time.sleep(0.25)
    err = node_job.first_error()
    if err and not (supervise and is_executor_lost(err)):
      tf_status["error"] = err

  threading.Thread(target=_watch_job, daemon=True,
                   name="node-job-watcher").start()

  # the supervisor starts BEFORE the reservation wait so executors dying
  # during bring-up are already relaunched (cluster_info is patched in
  # place as nodes register); only engine-hosted nodes are supervised —
  # driver_ps processes live on the driver machine outside any engine slot
  cluster_info: List[dict] = []
  supervisor = None
  if supervise:
    supervisor = ClusterSupervisor(
        engine, server, node_job, cluster_meta, cluster_info, engine_ids,
        tf_status, max_restarts=max_restarts, backoff=restart_backoff,
        backoff_cap=restart_backoff_cap).start()

  # the online consumer of the obs plane: a driver thread evaluating the
  # sink's rolling windows (stragglers, feed stalls, recompile storms,
  # serving saturation, memory slope). Alerts are counted + mirrored into
  # the supervisor event stream + JSONL'd + served over HEALTH — never
  # raised. Starts before the reservation wait so bring-up is covered.
  detector = None
  if server.obs_sink is not None:
    from tensorflowonspark_tpu.obs import anomaly as obs_anomaly
    if obs_anomaly.detect_enabled():
      # ONE driver ProcessLog, shared with the shutdown span/metrics dump
      # (TPUCluster._driver_obs_log) — two instances would write two meta
      # headers into the same obs-driver0-<pid>.jsonl
      rec = obs_spans.active()
      detector = obs_anomaly.AnomalyDetector(
          server.obs_sink, supervisor=supervisor,
          jsonl=_driver_obs_log(rec)).start()
      server.alert_source = detector

  def _abort_cleanup():
    if supervisor is not None:
      supervisor.stop()
    if detector is not None:
      detector.stop()
    server.stop()
    for p in driver_ps_procs:
      p.terminate()

  try:
    with TPUCluster._span("cluster.assemble", nodes=num_executors):
      cluster_info.extend(server.await_reservations(
          timeout=reservation_timeout, status=tf_status))
  except Exception:
    _abort_cleanup()
    raise

  # duplicate-node sanity check (parity: TFCluster.py:357-372)
  if server.reservations.duplicates:
    _abort_cleanup()
    raise RuntimeError(
        "duplicate node reservations detected (reused executors?): %r"
        % server.reservations.duplicates)

  logger.info("cluster of %d node(s) reserved: %s", len(cluster_info),
              [(n["executor_id"], n["job_name"], n["task_index"])
               for n in cluster_info])
  return TPUCluster(engine, cluster_info, cluster_meta, server, input_mode,
                    node_job, tf_status, driver_ps_procs=driver_ps_procs,
                    supervisor=supervisor, detector=detector)
