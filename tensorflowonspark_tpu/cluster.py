"""L3' cluster lifecycle API: reserve → launch → (feed) → shutdown.

Capability parity with the reference's ``TFCluster.py``
(/root/reference/tensorflowonspark/TFCluster.py), generalized over the engine
abstraction (Spark or the built-in LocalEngine) and re-targeted at JAX/TPU:

- ``run()`` builds the role template mapping job names → executor ids
  (reference :256-271), starts the rendezvous server (:283-285), launches the
  node bring-up job asynchronously so feeding can proceed (:318-336), awaits
  and validates reservations with duplicate detection (:357-372);
- ``train()``/``inference()`` implement the engine-pushes-rows input mode,
  with epochs via dataset replication (parity with epochs-via-RDD.union,
  :90-94);
- ``shutdown()`` is PS-aware, pushes end-of-feed into worker queues via a
  shutdown job (:174-176), remotely stops ps/evaluator nodes through their
  driver-reachable hubs (:186-194), enforces a watchdog timeout (default 3
  days, :136-144) and raises if any node failed (:179-183).
"""

import collections.abc
import logging
import os
import random
import signal
import threading
from typing import Dict, List, Optional, Sequence

from tensorflowonspark_tpu import node as node_mod
from tensorflowonspark_tpu.control import feedhub, rendezvous
from tensorflowonspark_tpu.engine.base import Engine

logger = logging.getLogger(__name__)


class InputMode(object):
  """How the cluster gets training data (parity: TFCluster.py:43-46).

  ``FILES`` (alias ``TENSORFLOW``): each node reads its own data shard
  (grain / tf.data / raw files from GCS or local disk); the engine only holds
  the executor slots.

  ``ENGINE`` (alias ``SPARK``): the engine pushes partitioned rows into each
  node's feed hub, consumed by the user fn through a DataFeed.
  """
  FILES = 0
  TENSORFLOW = 0
  ENGINE = 1
  SPARK = 1


class _StreamFeedHandle(object):
  """Progress of a hooked (D)Stream feed: micro-batches fed + stop flag."""

  def __init__(self):
    self.rounds = 0
    self.stopped = False


class TPUCluster(object):
  """Handle for a started cluster (parity: TFCluster.py:49-212)."""

  def __init__(self, engine: Engine, cluster_info: List[dict],
               cluster_meta: dict, server: rendezvous.Server,
               input_mode: int, node_job, tf_status: dict,
               driver_ps_procs: Sequence = ()):
    self.engine = engine
    self.cluster_info = cluster_info
    self.cluster_meta = cluster_meta
    self.server = server
    self.input_mode = input_mode
    self.node_job = node_job
    self.tf_status = tf_status
    self.queues = cluster_meta["queues"]
    self.driver_ps_procs = list(driver_ps_procs)

  # -- data plane ------------------------------------------------------------

  def train(self, data_partitions: Sequence, num_epochs: int = 0,
            feed_timeout: float = 600, qname: str = "input"):
    """Feed partitioned data to the cluster (ENGINE input mode only).

    Epochs are implemented by replicating the dataset ``num_epochs`` times
    (parity with epochs-via-RDD.union, reference TFCluster.py:90-94).
    Returns None for bounded data; a DStream argument returns the stream
    feed handle from :meth:`train_dstream`.
    """
    if hasattr(data_partitions, "foreachRDD"):
      # a Spark DStream handed straight to train(), exactly as the
      # reference accepted (TFCluster.py:83-85); the handle exposes
      # rounds-fed / stop-observed progress
      return self.train_dstream(data_partitions, feed_timeout=feed_timeout,
                                qname=qname)
    logger.info("feeding training data")
    assert self.input_mode == InputMode.ENGINE, \
        "train() requires InputMode.ENGINE/SPARK"
    epochs = max(1, num_epochs)
    parts = self._wrap_lazy(data_partitions)
    fn = node_mod.make_train_fn(self.cluster_info, self.cluster_meta,
                                feed_timeout=feed_timeout, qname=qname)
    if isinstance(parts, collections.abc.Iterator):
      # one-shot partition streams cannot be replayed (and _replicate's
      # fallback would drain the generator eagerly on the driver, feeding
      # epoch 1 and silently starving epochs 2..N), so route them through
      # the engine's lazy path. On LocalEngine the driver holds one window
      # of partitions in flight, never the whole dataset; SparkEngine's
      # _as_rdd still drains the stream into a driver-side list of
      # partition HANDLES before parallelize — O(dataset) only if the
      # stream carries raw rows instead of callables (use lazy handles or
      # train_dstream for big data on Spark)
      if epochs > 1:
        raise ValueError(
            "train(num_epochs=%d) got a one-shot partition iterator; "
            "re-iterable input (a list, an RDD, or lazy handles) is "
            "required to replay epochs" % epochs)
      stream = self.engine.map_partitions_lazy(parts, fn,
                                               timeout=feed_timeout)
      if isinstance(stream, collections.abc.Iterator):
        for _ in stream:   # windowed: one window in flight on the driver
          pass
      else:
        # RDD-like lazy result (SparkEngine hands back an uncollected
        # RDD): trigger the feed with a row-free action — count() runs
        # the tasks distributed and returns only a number
        stream.count()
      return
    parts = self._replicate(parts, epochs)
    self.engine.foreach_partition(parts, fn).wait()

  def train_stream(self, batch_stream, feed_timeout: float = 600,
                   qname: str = "input") -> int:
    """Feed an unbounded stream of partitioned datasets (micro-batches).

    The analog of the reference's Spark Streaming support
    (DStream.foreachRDD feeding, TFCluster.py:83-85): each item of
    ``batch_stream`` is a list of partitions fed as one round. A graceful
    stop request (``request_stop()``, or a remote
    ``rendezvous.Client(addr).request_stop()`` — parity with
    examples/utils/stop_streaming.py) ends the loop after the current
    round. Returns the number of rounds fed.
    """
    assert self.input_mode == InputMode.ENGINE, \
        "train_stream() requires InputMode.ENGINE/SPARK"
    rounds = 0
    for partitions in batch_stream:
      # feed first, check after: a batch already pulled from the source is
      # never discarded (sources may commit offsets on yield)
      self.train(partitions, num_epochs=1, feed_timeout=feed_timeout,
                 qname=qname)
      rounds += 1
      if self.server.done.is_set():
        logger.info("stop signal received; ending stream after %d rounds",
                    rounds)
        break
    return rounds

  def train_dstream(self, dstream, feed_timeout: float = 600,
                    qname: str = "input"):
    """Hook a Spark (D)Stream so every micro-batch RDD is fed as one round
    (parity: reference TFCluster.train wiring ``dataRDD.foreachRDD(_train)``,
    TFCluster.py:83-85).

    Feeding happens on Spark's streaming driver thread as batches arrive.
    After a graceful stop request (``request_stop()``, or a remote
    ``rendezvous.Client(addr).request_stop()`` — parity with
    examples/utils/stop_streaming.py) later micro-batches are skipped
    without being consumed, so the streaming job can be stopped and
    ``shutdown()`` called. Returns a handle whose ``rounds`` attribute
    counts the micro-batches fed so far and whose ``stopped`` flag reports
    whether the stop signal has been observed.
    """
    assert self.input_mode == InputMode.ENGINE, \
        "train_dstream() requires InputMode.ENGINE/SPARK"
    fn = node_mod.make_train_fn(self.cluster_info, self.cluster_meta,
                                feed_timeout=feed_timeout, qname=qname)
    handle = _StreamFeedHandle()

    def _feed(rdd):
      if self.server.done.is_set():
        if not handle.stopped:
          logger.info("stop signal received; skipping further micro-batches "
                      "after %d rounds", handle.rounds)
        handle.stopped = True
        return
      self.engine.foreach_partition(rdd, fn).wait()
      handle.rounds += 1

    dstream.foreachRDD(_feed)
    return handle

  def foreach_batch(self, feed_timeout: float = 600, qname: str = "input"):
    """A ``(batch_df, batch_id) -> None`` callback for Structured Streaming:
    ``query = df.writeStream.foreachBatch(cluster.foreach_batch()).start()``.

    The modern equivalent of the DStream hook above: each micro-batch
    DataFrame is fed as one round; after a stop request batches are
    skipped. The reference predates Structured Streaming — this is the
    same capability on the current Spark API.
    """
    assert self.input_mode == InputMode.ENGINE, \
        "foreach_batch() requires InputMode.ENGINE/SPARK"
    fn = node_mod.make_train_fn(self.cluster_info, self.cluster_meta,
                                feed_timeout=feed_timeout, qname=qname)

    def _feed(batch_df, batch_id):
      if self.server.done.is_set():
        return
      self.engine.foreach_partition(batch_df, fn).wait()

    return _feed

  def request_stop(self) -> None:
    """Signal streaming feeds to stop after the current round."""
    self.server.done.set()

  @property
  def server_addr(self):
    """Rendezvous address — remote processes can send the streaming stop
    signal here via ``rendezvous.Client(addr).request_stop()``."""
    return self.server.addr

  def inference(self, data_partitions: Sequence, feed_timeout: float = 600,
                qname: str = "input", collect: bool = True):
    """Feed data for inference (parity: TFCluster.inference, reference
    TFCluster.py:96-115).

    With ``collect=True`` (default) results are gathered into a driver-side
    list — fine for small jobs. With ``collect=False`` the return value is
    the engine's lazy handle (Spark: the uncollected result RDD, exactly
    like the reference; LocalEngine: a streaming generator holding at most
    one window of partitions), so cluster-scale inference output never
    materializes on the driver.
    """
    logger.info("feeding inference data")
    assert self.input_mode == InputMode.ENGINE, \
        "inference() requires InputMode.ENGINE/SPARK"
    fn = node_mod.make_inference_fn(self.cluster_info, self.cluster_meta,
                                    feed_timeout=feed_timeout, qname=qname)
    data_partitions = self._wrap_lazy(data_partitions)
    if collect:
      return self.engine.map_partitions(data_partitions, fn)
    return self.engine.map_partitions_lazy(data_partitions, fn,
                                           timeout=feed_timeout)

  # -- lifecycle -------------------------------------------------------------

  def shutdown(self, grace_secs: float = 0, timeout: int = 259200) -> None:
    """Stop the cluster; raise if any node failed.

    ``timeout`` arms a SIGALRM watchdog (3-day default) guarding against
    hung shutdowns (parity: TFCluster.py:117,136-144).
    """
    in_main = threading.current_thread() is threading.main_thread()
    if timeout and in_main:
      def _watchdog(signum, frame):
        raise TimeoutError("cluster shutdown watchdog fired after %ds" % timeout)
      old = signal.signal(signal.SIGALRM, _watchdog)
      signal.alarm(int(timeout))
    try:
      self._shutdown_inner(grace_secs)
    finally:
      if timeout and in_main:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)

  def _shutdown_inner(self, grace_secs: float) -> None:
    workers = [n for n in self.cluster_info
               if n["job_name"] in node_mod.JAX_ROLES]
    background = [n for n in self.cluster_info
                  if n["job_name"] in node_mod.BACKGROUND_ROLES]

    if self.input_mode == InputMode.ENGINE:
      # push end-of-feed markers through a shutdown job on free (worker)
      # executors (parity: TFCluster.py:174-176)
      fn = node_mod.make_shutdown_fn(
          self.cluster_info, self.cluster_meta, grace_secs=grace_secs,
          queues=[q for q in self.queues if q not in ("error", "output",
                                                      "control")])
      self.engine.foreach_partition([[n["executor_id"]] for n in workers],
                                    fn).wait()
    elif any(n.get("tb_url") for n in self.cluster_info):
      # FILES mode has no feed-shutdown job; still reap the TensorBoard the
      # chief spawned. One PINNED task per executor slot (shared-queue tasks
      # could all land on one free executor and miss the chief's), each
      # best-effort so a dead node can't abort the rest of shutdown.
      fn = node_mod.make_tb_kill_fn(self.cluster_info, self.cluster_meta)
      try:
        self.engine.run_on_executors(
            fn, num_tasks=self.engine.num_executors).wait(
                raise_on_error=False)
      except Exception as e:  # noqa: BLE001 - reap is best-effort
        logger.warning("tensorboard reap job failed: %s", e)

    # stop ps/evaluator nodes by reaching their remote hubs directly
    # (parity: TFCluster.py:186-194)
    for n in background:
      try:
        hub = feedhub.connect(tuple(n["hub_addr"]),
                              self.cluster_meta["authkey"])
        hub.get_queue("control").put(None, block=True, timeout=30)
      except Exception as e:  # noqa: BLE001 - best-effort stop of sidecars
        logger.warning("failed to stop %s:%d: %s", n["job_name"],
                       n["task_index"], e)

    # driver-hosted ps processes exit once their control queue gets None
    for p in self.driver_ps_procs:
      p.join(timeout=60)
      if p.is_alive():
        logger.warning("driver ps process %s did not exit; terminating",
                       p.name)
        p.terminate()

    # wait for the node bring-up job itself (foreground workers return when
    # the user fn finishes); propagate node errors
    self.node_job.wait(raise_on_error=False)
    self.server.stop()
    err = self.node_job.first_error() or self.tf_status.get("error")
    if err:
      raise RuntimeError("cluster shutdown with node error:\n%s" % err)
    logger.info("cluster shutdown complete")

  def tensorboard_url(self) -> Optional[str]:
    """URL of the TensorBoard server, if one was launched (parity:
    TFCluster.tensorboard_url, TFCluster.py:207-212)."""
    for n in self.cluster_info:
      if n.get("tb_url"):
        return n["tb_url"]
    return None

  @staticmethod
  def _wrap_lazy(parts):
    """Bare-callable partitions (lazy handles, e.g. from
    ``load_tfrecords(lazy=True)``) become single-item partitions the
    feeders resolve executor-side (node._materialize_partition).
    Engine-native handles and row partitions pass through untouched."""
    if hasattr(parts, "mapPartitions") or hasattr(parts, "rdd") \
        or hasattr(parts, "foreachRDD"):
      return parts
    if isinstance(parts, collections.abc.Iterator):
      # a one-shot stream of partitions (the collect=False windowed path)
      # must stay a stream — the driver pulls one window at a time
      return ([p] if callable(p) else p for p in parts)
    # any re-iterable collection wraps eagerly (epoch replication
    # re-iterates it)
    return [[p] if callable(p) else p for p in parts]

  @staticmethod
  def _replicate(parts: Sequence, epochs: int):
    """Repeat the dataset ``epochs`` times without touching its rows.

    Engine-native handles (an RDD, or a DataFrame wrapping one) replicate
    via ``union`` — the reference's epochs idiom (``sc.union([rdd]*N)``,
    TFCluster.py:90-94) — so the driver never iterates cluster data.
    Driver-side partition lists are simply concatenated.
    """
    if hasattr(parts, "rdd"):           # DataFrame → its RDD
      parts = parts.rdd
    if hasattr(parts, "mapPartitions"):  # RDD-like: epochs via union
      out = parts
      for _ in range(epochs - 1):
        out = out.union(parts)
      return out
    out = []
    for _ in range(epochs):
      out.extend(parts)
    return out


def run(engine: Engine, main_fn, tf_args=None,
        num_executors: Optional[int] = None, num_ps: int = 0,
        tensorboard: bool = False, input_mode: int = InputMode.FILES,
        log_dir: Optional[str] = None, driver_ps_nodes: bool = False,
        master_node: Optional[str] = None,
        reservation_timeout: float = 600,
        queues: Sequence[str] = ("input", "output", "error", "control"),
        eval_node: bool = False, release_port: bool = True,
        chips_per_node: int = 0, qmax: int = 1024,
        feed_transport: str = "auto",
        shm_capacity: int = 64 * 1024 * 1024) -> TPUCluster:
  """Start a cluster and run ``main_fn(tf_args, ctx)`` on every node.

  Signature parity with the reference's ``TFCluster.run``
  (TFCluster.py:215-245), with the engine abstraction in place of a
  SparkContext and TPU chip allocation in place of GPU counts.
  ``driver_ps_nodes`` hosts the ps nodes on the driver machine so every
  engine executor keeps its accelerator for workers (parity :229,298-316;
  FILES input mode only, like the reference).
  """
  num_executors = num_executors or engine.num_executors
  if feed_transport == "auto":
    # shared-memory rings require the feeder task and the node to share a
    # host, which only engines with colocated executors guarantee; the
    # node itself still falls back to "queue" if the native ring is absent
    feed_transport = "shm" if getattr(engine, "colocated_executors", False) \
        else "queue"
  if driver_ps_nodes and input_mode != InputMode.FILES:
    raise ValueError("driver_ps_nodes requires InputMode.FILES/TENSORFLOW "
                     "(parity with the reference)")
  engine_nodes = num_executors - (num_ps if driver_ps_nodes else 0)
  if engine_nodes > engine.num_executors:
    raise ValueError("cluster of %d nodes needs %d executors but engine has %d"
                     % (num_executors, engine_nodes, engine.num_executors))

  # role template (parity: TFCluster.py:256-271): ps nodes first, then
  # master/chief, evaluator, workers
  num_master = 1 if master_node else 0
  num_eval = 1 if eval_node else 0
  num_workers = max(num_executors - num_ps - num_eval - num_master, 0)
  total = num_ps + num_master + num_eval + num_workers
  assert total == num_executors, \
      "cluster requires %d nodes but %d executors reserved" % (total,
                                                               num_executors)
  assert num_master + num_workers > 0, \
      "cluster requires at least one worker or master/chief node"
  if num_ps > 0:
    logger.warning(
        "num_ps=%d: parameter servers are API-compatible but architecturally "
        "obsolete on TPU — synchronous data parallelism over ICI is the "
        "native strategy; ps nodes will run as background sidecars", num_ps)

  executors = list(range(num_executors))
  cluster_template: Dict[str, List[int]] = {}
  idx = 0
  if num_ps:
    cluster_template["ps"] = executors[idx:idx + num_ps]
    idx += num_ps
  if num_master:
    cluster_template[master_node] = executors[idx:idx + 1]
    idx += 1
  if num_eval:
    cluster_template["evaluator"] = executors[idx:idx + 1]
    idx += 1
  if num_workers:
    cluster_template["worker"] = executors[idx:]
  logger.info("cluster template: %s", cluster_template)

  server = rendezvous.Server(num_executors)
  server_addr = server.start()

  cluster_meta = {
      "id": random.getrandbits(64),
      "cluster_template": cluster_template,
      "num_executors": num_executors,
      "server_addr": list(server_addr),
      "authkey": os.urandom(16),
      "queues": list(queues),
      "input_mode": input_mode,
      "default_fs": engine.default_fs(),
      "reservation_timeout": reservation_timeout,
      "tensorboard": tensorboard,
      "log_dir": log_dir,
      "release_port": release_port,
      "chips_per_node": chips_per_node,
      "qmax": qmax,
      # "queue" (manager-proxy, works everywhere) or "shm" (native
      # shared-memory ring for the input stream; single host or per-host).
      # The default "auto" resolved above: shm on colocated engines.
      "feed_transport": feed_transport,
      "shm_capacity": max(shm_capacity, 8 * 1024 * 1024),
  }

  # launch node bring-up asynchronously so that (a) feeding can start and
  # (b) reservation failures surface through tf_status (parity :318-336)
  tf_status: Dict[str, Optional[str]] = {"error": None}
  node_fn = node_mod.make_node_fn(main_fn, tf_args, cluster_meta)

  driver_ps_procs = []
  if driver_ps_nodes and num_ps:
    # ps nodes run on the driver machine in their own processes/workdirs
    import cloudpickle
    import multiprocessing as mp
    import tempfile
    mapfn_bytes = cloudpickle.dumps(node_fn)
    ctx_mp = mp.get_context("spawn")
    for ps_id in cluster_template["ps"]:
      wd = tempfile.mkdtemp(prefix="tos_driver_ps_%d_" % ps_id)
      p = ctx_mp.Process(target=node_mod.driver_node_main,
                         args=(mapfn_bytes, ps_id, wd),
                         name="driver-ps-%d" % ps_id)
      p.start()
      driver_ps_procs.append(p)
    engine_ids = [i for i in executors if i not in cluster_template["ps"]]
  else:
    engine_ids = executors

  node_job = engine.run_on_executors(node_fn, num_tasks=len(engine_ids),
                                     task_payloads=engine_ids)

  def _watch_job():
    # poll: a single failed bring-up task must surface its traceback
    # immediately (aborting await_reservations), not after the surviving
    # tasks run out their reservation timeout; driver-hosted ps processes
    # get the same treatment (a crashed child has a nonzero exitcode)
    import time as _time
    while not node_job.done():
      err = node_job.first_error()
      for p in driver_ps_procs:
        if p.exitcode not in (None, 0):
          err = err or ("driver ps process %s exited with code %s during "
                        "bring-up" % (p.name, p.exitcode))
      if err:
        tf_status["error"] = err
        return
      _time.sleep(0.25)
    err = node_job.first_error()
    if err:
      tf_status["error"] = err

  threading.Thread(target=_watch_job, daemon=True,
                   name="node-job-watcher").start()

  try:
    cluster_info = server.await_reservations(
        timeout=reservation_timeout, status=tf_status)
  except Exception:
    server.stop()
    for p in driver_ps_procs:
      p.terminate()
    raise

  # duplicate-node sanity check (parity: TFCluster.py:357-372)
  if server.reservations.duplicates:
    server.stop()
    for p in driver_ps_procs:
      p.terminate()
    raise RuntimeError(
        "duplicate node reservations detected (reused executors?): %r"
        % server.reservations.duplicates)

  logger.info("cluster of %d node(s) reserved: %s", len(cluster_info),
              [(n["executor_id"], n["job_name"], n["task_index"])
               for n in cluster_info])
  return TPUCluster(engine, cluster_info, cluster_meta, server, input_mode,
                    node_job, tf_status, driver_ps_procs=driver_ps_procs)
