"""L4' user-code API: consume engine-fed data inside the main fn.

Capability parity with the reference's ``TFNode.DataFeed``
(/root/reference/tensorflowonspark/TFNode.py:234-343):

- ``next_batch(n)`` pulls up to ``n`` items; ``None`` marks end-of-feed
  (sets ``should_stop``); ``EndPartition`` is skipped in train mode but ends
  the batch early in inference mode so results stay aligned per partition
  (reference :278-301);
- ``batch_results`` pushes inference outputs to the output queue (:307-318);
- ``terminate()`` flips the hub state to ``'terminating'`` and drains the
  input queue so blocked feeders finish (:320-343);
- ``input_mapping`` transposes row-tuples into a dict of named columns
  (:251,274,294-298).

TPU-first difference: items move through the hub in chunks
(``get_many``/``put_many``), one manager round-trip per batch rather than per
row, and ``to_device_arrays`` stages a batch into device HBM.
"""

import collections
import logging
import time
from typing import Dict, List, Optional, Sequence

from tensorflowonspark_tpu.control.marker import EndPartition, Marker

logger = logging.getLogger(__name__)


class FeedStalledError(TimeoutError):
  """The feed produced no data (and no end-of-feed marker) for longer than
  ``liveness_timeout`` — the feeder process is presumed dead."""


class DataFeed(object):
  """Pull-based reader over this node's feed hub."""

  def __init__(self, hub, train_mode: bool = True, qname_in: str = "input",
               qname_out: str = "output",
               input_mapping: Optional[Dict[str, str]] = None,
               liveness_timeout: Optional[float] = 600.0):
    self.hub = hub
    self.train_mode = train_mode
    self.qname_in = qname_in
    self.qname_out = qname_out
    self.liveness_timeout = liveness_timeout
    self.done_feeding = False
    # sorted-column order matches the estimator's dataset.select(sorted(...))
    # convention (reference pipeline.py:414, TFNode.py:251)
    self.input_tensors = ([input_mapping[col] for col in
                           sorted(input_mapping)] if input_mapping else None)
    # the input stream rides the shared-memory ring when the node
    # advertises one (feed_transport='shm'), PLUS the hub queue for
    # feeders on other hosts; output/control stay on the hub
    from tensorflowonspark_tpu.node import consumer_channel
    self._queue_in = consumer_channel(hub, qname_in)
    self._queue_out = hub.get_queue(qname_out)
    self._buffer = collections.deque()

  def _check_liveness(self, stalled_since: float) -> None:
    """Raise instead of polling forever when the producer side died.

    A feeder that crashes without pushing markers leaves ``next_batch``'s
    empty-poll loop spinning (the error queue was only read by feeder/
    shutdown tasks — VERDICT r2 weakness 6). On each empty poll: surface
    worker/feeder tracebacks from the error queue (peek-and-put-back, same
    protocol as node._check_errors, parity TFSparkNode.py:508-515), honor a
    hub moved to ``terminating``/``stopped``, and give up after
    ``liveness_timeout`` seconds without data.
    """
    from tensorflowonspark_tpu.node import _check_errors
    _check_errors(self.hub, "next_batch")
    try:
      state = self.hub.get("state")
    except Exception:  # noqa: BLE001 - hub manager itself may be gone
      raise FeedStalledError("feed hub is unreachable from next_batch — "
                             "the node's manager process died")
    if state in ("terminating", "stopped"):
      logger.info("hub state %r during next_batch; stopping feed", state)
      self.done_feeding = True
      return
    if (self.liveness_timeout is not None
        and time.monotonic() - stalled_since > self.liveness_timeout):
      raise FeedStalledError(
          "no data and no end-of-feed marker for %.0fs (hub state %r) — "
          "feeder presumed dead" % (self.liveness_timeout, state))

  def next_batch(self, batch_size: int):
    """Return up to ``batch_size`` items (or a dict of columns when an
    input_mapping is configured). Blocks until data arrives.

    Raises :class:`FeedStalledError` (or the worker's own error, re-raised
    from the error queue) instead of blocking forever when the producer
    side has died; see ``liveness_timeout``.
    """
    batch: List = []
    stalled_since = time.monotonic()
    while len(batch) < batch_size:
      if not self._buffer:
        got = self._queue_in.get_many(batch_size - len(batch), block=True,
                                      timeout=1.0)
        if not got:
          if self.done_feeding:
            break
          self._check_liveness(stalled_since)
          continue
        stalled_since = time.monotonic()
        self._queue_in.task_done(len(got))
        self._buffer.extend(got)
      item = self._buffer.popleft()
      if item is None:
        logger.info("end-of-feed marker received")
        self.done_feeding = True
        break
      if isinstance(item, (Marker, EndPartition)):
        if self.train_mode:
          continue
        break  # inference: batch ends at the partition boundary
      batch.append(item)

    if self.input_tensors is None:
      return batch
    # transpose rows -> named columns
    cols: Dict[str, List] = {name: [] for name in self.input_tensors}
    for row in batch:
      for name, value in zip(self.input_tensors, row):
        cols[name].append(value)
    return cols

  def should_stop(self) -> bool:
    """True once the end-of-feed marker was consumed (parity :303-305)."""
    return self.done_feeding

  def batch_results(self, results: Sequence,
                    timeout: Optional[float] = None) -> None:
    """Push a batch of inference results (parity :307-318).

    Bounded (TOS001): the push blocks at most ``timeout`` seconds
    (default: this feed's ``liveness_timeout``). An unbounded put here
    wedged the node forever when the inference collector died — the
    worker kept its executor busy and a pinned relaunch could never
    schedule behind it (the PR 1 slot-deadlock class).
    """
    timeout = timeout if timeout is not None else self.liveness_timeout
    try:
      self._queue_out.put_many(list(results), block=True, timeout=timeout)
    except Exception as e:  # noqa: BLE001 - recast ONLY the queue-full
      # timeout (which may arrive as a proxy-re-raised feedhub.QueueFull)
      if type(e).__name__ != "QueueFull":
        raise
      admitted = getattr(e, "admitted", 0)
      err = FeedStalledError(
          "output queue still full after %.0fs pushing %d result(s) (%d "
          "already enqueued — skip them on retry) — the inference collector "
          "is presumed dead" % (timeout or 0, len(results), admitted))
      # a timed-out put_many may have enqueued a prefix; callers that retry
      # must resume at results[admitted:] or they double-deliver
      err.admitted = admitted
      raise err from e

  def terminate(self) -> None:
    """Request early termination: mark the hub terminating and drain the
    input queue so blocked feeders can finish (parity :320-343)."""
    logger.info("terminate() requested; draining input queue")
    self.hub.set("state", "terminating")
    self.done_feeding = True
    empty_rounds = 0
    while empty_rounds < 3:
      got = self._queue_in.get_many(512, block=True, timeout=1.0)
      if got:
        self._queue_in.task_done(len(got))
        empty_rounds = 0
      else:
        empty_rounds += 1

  def next_batch_synced(self, batch_size: int):
    """``next_batch`` with global step agreement across jax processes.

    Synchronous SPMD training deadlocks if one worker's feed runs dry while
    others enter a collective. Before handing out a batch, all processes
    vote "I have a full batch"; if anyone is short, EVERY process stops
    (returning a batch signalling stop via ``should_stop()``). At most one
    partial batch per worker is discarded at end-of-data — the principled
    replacement for the reference's train-90%-of-steps workaround
    (examples/mnist/keras/mnist_spark.py:58-64).
    """
    from tensorflowonspark_tpu.parallel.collectives import \
        all_processes_agree
    batch = self.next_batch(batch_size)
    n = len(batch[self.input_tensors[0]]) if isinstance(batch, dict) \
        else len(batch)
    ok = n == batch_size and not self.done_feeding
    if not all_processes_agree(ok):
      self.done_feeding = True
      return {k: [] for k in batch} if isinstance(batch, dict) else []
    return batch

  # -- TPU staging -----------------------------------------------------------

  def next_batch_arrays(self, batch_size: int, dtype=None):
    """Like ``next_batch`` but returns stacked numpy arrays, ready for
    ``jax.device_put`` (host-staging step of the feed plane redesign)."""
    import numpy as np
    batch = self.next_batch(batch_size)
    if isinstance(batch, dict):
      return {k: np.asarray(v, dtype=dtype) for k, v in batch.items()}
    return np.asarray(batch, dtype=dtype)


def drain_pending_rows(hub, qname: str = "input", settle_rounds: int = 3,
                       settle_timeout: float = 0.1) -> List:
  """Pull every undelivered row out of a (presumed dead) node's feed queue.

  Fault-recovery primitive: when a worker dies mid-feed, rows already
  pushed into its hub queue would otherwise be lost — and the feeder tasks
  blocked in ``queue.join()`` would wedge until their feed timeout. This
  drains the queue, acking each batch with ``task_done`` so blocked
  feeders complete, and returns the data rows for requeueing through the
  engine feed path (``ClusterSupervisor`` refeeds them to live workers).

  End-of-feed / partition markers are dropped, not returned: the requeued
  rows ride a fresh feed round with its own markers. The drain keeps
  sweeping until ``settle_rounds`` consecutive empty polls, catching a
  feeder caught mid-``put_many``.

  Only call this against a hub whose consumer is KNOWN dead — draining a
  live node's queue steals its input.
  """
  queue = hub.get_queue(qname)
  rows: List = []
  empty = 0
  while empty < settle_rounds:
    got = queue.get_many(1024, block=True, timeout=settle_timeout)
    if not got:
      empty += 1
      continue
    empty = 0
    queue.task_done(len(got))
    rows.extend(r for r in got
                if r is not None and not isinstance(r, Marker))
  return rows


def prefetch_to_device(batches, size: int = 2, device=None):
  """Overlap host→device staging with device compute.

  Wraps an iterator of host batches (numpy arrays / pytrees of them) and
  yields device-resident batches, keeping up to ``size`` transfers in
  flight: ``jax.device_put`` is asynchronous, so batch N+1's PCIe/ICI
  transfer runs while the caller's jitted step for batch N executes —
  the standard TPU input-pipeline trick, packaged for DataFeed loops::

      def host_batches():
          while not feed.should_stop():
              b = feed.next_batch_arrays(B)
              if len(b):           # [] after the end-of-feed marker
                  yield b
      for x in prefetch_to_device(host_batches(), size=2):
          state, loss = step(state, x)

  With ``size=1`` this degrades to plain ``device_put`` per batch. The
  buffer holds ``size`` batches in device memory — keep it small. Note
  the fill also gates startup: the first batch is yielded only once
  ``size`` batches have staged (or the source ends), so a large ``size``
  on a slow feed delays step 0 by ``size`` batch-fetches.
  Delegates to ``data.readers.device_prefetch`` — the FILES-mode input
  pipeline's prefetcher — so there is exactly ONE implementation of the
  overlap trick (``device`` may also be a sharding for SPMD staging).
  """
  from tensorflowonspark_tpu.data.readers import device_prefetch
  return device_prefetch(batches, size=size, sharding=device)
