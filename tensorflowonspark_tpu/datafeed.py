"""L4' user-code API: consume engine-fed data inside the main fn.

Capability parity with the reference's ``TFNode.DataFeed``
(/root/reference/tensorflowonspark/TFNode.py:234-343):

- ``next_batch(n)`` pulls up to ``n`` items; ``None`` marks end-of-feed
  (sets ``should_stop``); ``EndPartition`` is skipped in train mode but ends
  the batch early in inference mode so results stay aligned per partition
  (reference :278-301);
- ``batch_results`` pushes inference outputs to the output queue (:307-318);
- ``terminate()`` flips the hub state to ``'terminating'`` and drains the
  input queue so blocked feeders finish (:320-343);
- ``input_mapping`` transposes row-tuples into a dict of named columns
  (:251,274,294-298).

TPU-first difference — the COLUMNAR feed plane: items move through the hub
in chunk-boundary envelopes (one codec-encoded chunk per transport unit,
``control/chunkcodec.py``), and the feed keeps a chunk-granular buffer.
Homogeneous array chunks stay columnar from the feeder all the way to
batch assembly: ``next_batch_arrays`` / ``input_mapping`` batches are built
by SLICING AND CONCATENATING column ndarray views across chunk boundaries
— no per-row Python loop; the single copy happens at the concatenation
that hands the batch off (which also makes handed-off batches immune to
ring-slot reuse). Heterogeneous / pickle chunks and the row-list
``next_batch`` API fall back to row materialization with unchanged
semantics. A bounded background fetch thread (``TOS_FEED_PIPELINE``)
pipelines hub RPCs + decode under the caller's jitted step, composing
with ``prefetch_to_device`` double-buffering for the host→device leg.
"""

import collections
import logging
import os
import queue as std_queue
import threading
import time
from typing import Dict, List, Optional, Sequence

from tensorflowonspark_tpu.control import chunkcodec
from tensorflowonspark_tpu.control.marker import EndPartition, Marker
from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans

logger = logging.getLogger(__name__)

#: depth of the background fetch pipeline (chunks buffered ahead of the
#: consumer); 0 disables the fetch thread (env registry: TOS008)
ENV_FEED_PIPELINE = "TOS_FEED_PIPELINE"

#: raw-row gather cap per chunk fetch (legacy unframed streams only —
#: envelope chunks keep their own boundaries)
DEFAULT_FETCH_ROWS = 1024

#: bound on every blocking wait inside the fetch thread (TOS001: a wedged
#: hub must never pin the thread past its stop flag check)
_PIPELINE_POLL = 0.5


class FeedStalledError(TimeoutError):
  """The feed produced no data (and no end-of-feed marker) for longer than
  ``liveness_timeout`` — the feeder process is presumed dead."""


def _chunk_weight(got) -> int:
  """task_done weight of one ``get_chunk`` wire unit."""
  kind = got[0]
  if kind == "enc":
    return got[1]
  if kind == "rows":
    return len(got[1])
  if kind == "data":
    chunk = got[1]
    return chunk.n if isinstance(chunk, chunkcodec.ColumnChunk) \
        else len(chunk)
  return 1  # marker


def _fetch_chunk(channel, max_rows: int, timeout, stats=None):
  """One chunk-granular fetch + ack off ``channel``.

  Normalizes every transport's wire format to ``("data", ColumnChunk |
  row_list)`` / ``("marker", m)`` / ``None`` (timeout), acking the
  channel with the unit's row weight immediately after the fetch (the
  same eager-ack the row path always used)."""
  t0 = time.perf_counter() if stats is not None else 0.0
  got = channel.get_chunk(max_rows, block=True, timeout=timeout)
  if stats is not None:
    stats["fetch_s"] += time.perf_counter() - t0
  if not got:
    return None
  channel.task_done(_chunk_weight(got))
  kind = got[0]
  if kind != "enc":
    if kind == "rows":
      return ("data", got[1])
    return got  # already normalized ("data", ...) / ("marker", m)
  t0 = time.perf_counter() if stats is not None else 0.0
  chunk = chunkcodec.decode_columns(got[2])
  if stats is not None:
    stats["decode_s"] += time.perf_counter() - t0
  return chunkcodec.classify_decoded(chunk)


class _FetchPipeline(object):
  """Bounded background chunk fetcher (the hub-RPC overlap plane).

  One daemon thread repeats ``_fetch_chunk`` into a depth-bounded local
  queue so the manager round-trip AND the msgpack decode of chunk N+1 run
  under the caller's jitted step for chunk N. Every blocking call is
  timeout-bounded (TOS001); a fetch error is forwarded and re-raised in
  the consumer; the thread retires itself at end-of-feed.
  """

  def __init__(self, channel, depth: int, max_rows: int, stats):
    self._channel = channel
    self._max_rows = max_rows
    self._stats = stats
    self._out = std_queue.Queue(maxsize=max(1, depth))
    self._stop = threading.Event()
    self._thread = threading.Thread(target=self._run, daemon=True,
                                    name="tos-feed-fetch")
    self._thread.start()

  def _run(self):
    while not self._stop.is_set():
      try:
        got = _fetch_chunk(self._channel, self._max_rows,
                           timeout=_PIPELINE_POLL, stats=self._stats)
      except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
        self._forward(("err", e))
        return
      if got is None:
        continue
      if not self._forward(got):
        return
      if got[0] == "marker" and got[1] is None:
        return  # end-of-feed: the stream is over, retire the thread

  def _forward(self, item) -> bool:
    while not self._stop.is_set():
      try:
        self._out.put(item, timeout=_PIPELINE_POLL)
        return True
      except std_queue.Full:
        continue
    return False

  def get(self, timeout: float):
    """Next fetched chunk, or None; re-raises a fetch-thread error."""
    try:
      item = self._out.get(timeout=timeout)
    except std_queue.Empty:
      return None
    if item[0] == "err":
      raise item[1]
    return item

  def stop(self) -> None:
    """Stop the thread and discard buffered chunks (already acked)."""
    self._stop.set()
    self._thread.join(timeout=5.0)
    while True:
      try:
        self._out.get(block=False)
      except std_queue.Empty:
        return


class DataFeed(object):
  """Pull-based reader over this node's feed hub."""

  def __init__(self, hub, train_mode: bool = True, qname_in: str = "input",
               qname_out: str = "output",
               input_mapping: Optional[Dict[str, str]] = None,
               liveness_timeout: Optional[float] = 600.0,
               pipeline_depth: Optional[int] = None):
    self.hub = hub
    self.train_mode = train_mode
    self.qname_in = qname_in
    self.qname_out = qname_out
    self.liveness_timeout = liveness_timeout
    self.done_feeding = False
    # sorted-column order matches the estimator's dataset.select(sorted(...))
    # convention (reference pipeline.py:414, TFNode.py:251)
    self.input_tensors = ([input_mapping[col] for col in
                           sorted(input_mapping)] if input_mapping else None)
    # the input stream rides the shared-memory ring when the node
    # advertises one (feed_transport='shm'), PLUS the hub queue for
    # feeders on other hosts; output/control stay on the hub
    from tensorflowonspark_tpu.node import consumer_channel
    self._queue_in = consumer_channel(hub, qname_in)
    self._queue_out = hub.get_queue(qname_out)
    #: chunk-granular buffer: ["cols", ColumnChunk, offset] (mutable — the
    #: offset advances as batches slice the chunk), ("rows", deque) for
    #: heterogeneous/legacy chunks, ("marker", m) for chunk-boundary markers
    self._chunks = collections.deque()
    if pipeline_depth is None:
      pipeline_depth = int(os.environ.get(ENV_FEED_PIPELINE, "2"))
    self._pipeline_depth = max(0, pipeline_depth)
    self._pipeline: Optional[_FetchPipeline] = None
    #: per-stage accounting (seconds / counts), filled on the hot path —
    #: tools/feed_bench.py reads this for its breakdown (snapshot it with
    #: :meth:`stats_snapshot`, never by zeroing: the fetch thread keeps
    #: read-modify-writing these entries)
    self.stats = {"fetch_s": 0.0, "decode_s": 0.0, "assemble_s": 0.0,
                  "chunks": 0, "columnar_chunks": 0, "aligned_batches": 0}
    # obs seam (docs/OBSERVABILITY.md): cached once so the disabled case
    # is one None check per batch
    self._rec = obs_spans.active()
    self._obs_stage_t = 0.0   # last empty-poll stage-gauge mirror
    reg = obs_metrics.active()
    self._obs_m = None if reg is None else {
        "batches": reg.counter("feed.batches"),
        "rows": reg.counter("feed.rows"),
        "fetch_s": reg.gauge("feed.fetch_s"),
        "decode_s": reg.gauge("feed.decode_s"),
        "assemble_s": reg.gauge("feed.assemble_s"),
        "chunks": reg.gauge("feed.chunks"),
        "batch_ms": reg.histogram("feed.batch_ms"),
    }

  def stats_snapshot(self) -> obs_metrics.StatsSnapshot:
    """Subtraction baseline over the LIVE ``stats`` dict — the one safe
    way to read steady-state stage deltas while the fetch thread keeps
    mutating them (obs.metrics.StatsSnapshot)."""
    return obs_metrics.snapshot_stats(self.stats)

  def _obs_stages(self) -> None:
    """Mirror the live stage seconds into the registry gauges."""
    m = self._obs_m
    m["fetch_s"].set(self.stats["fetch_s"])
    m["decode_s"].set(self.stats["decode_s"])
    m["assemble_s"].set(self.stats["assemble_s"])
    m["chunks"].set(self.stats["chunks"])

  def _obs_batch(self, t0: float, n: int) -> None:
    """Record one delivered batch into the obs plane (active only)."""
    dt = time.monotonic() - t0
    if self._rec is not None:
      self._rec.record_span("feed.batch", t0, dt, rows=n)
    m = self._obs_m
    if m is not None:
      m["batches"].inc()
      if n:
        m["rows"].inc(n)
      m["batch_ms"].observe(dt * 1e3)
      self._obs_stages()

  # -- fetch plane -----------------------------------------------------------

  def _fetch(self, timeout: float = 1.0) -> bool:
    """One fetch attempt; True if a chunk entry was appended."""
    if self._pipeline_depth > 0:
      if self._pipeline is None:
        self._pipeline = _FetchPipeline(self._queue_in, self._pipeline_depth,
                                        DEFAULT_FETCH_ROWS, self.stats)
      got = self._pipeline.get(timeout)
    else:
      got = _fetch_chunk(self._queue_in, DEFAULT_FETCH_ROWS,
                         timeout=timeout, stats=self.stats)
    if got is None:
      # a STALLED consumer delivers no batches, so batch-boundary gauge
      # mirroring freezes exactly when the feed-stall detector needs the
      # stage seconds to keep moving — mirror them on empty polls too
      # (throttled: the poll loop can spin at sub-second cadence)
      if self._obs_m is not None:
        now = time.monotonic()
        if now - self._obs_stage_t >= 0.5:
          self._obs_stage_t = now
          self._obs_stages()
      return False
    kind, payload = got
    if kind == "marker":
      self._chunks.append(("marker", payload))
      return True
    self.stats["chunks"] += 1
    if isinstance(payload, chunkcodec.ColumnChunk):
      self.stats["columnar_chunks"] += 1
      self._chunks.append(["cols", payload, 0])
    else:
      self._chunks.append(("rows", collections.deque(payload)))
    return True

  def _stop_pipeline(self) -> None:
    """Retire the fetch thread (already-acked buffered chunks discard)."""
    if self._pipeline is not None:
      self._pipeline.stop()
      self._pipeline = None

  def _check_liveness(self, stalled_since: float) -> None:
    """Raise instead of polling forever when the producer side died.

    A feeder that crashes without pushing markers leaves ``next_batch``'s
    empty-poll loop spinning (the error queue was only read by feeder/
    shutdown tasks — VERDICT r2 weakness 6). On each empty poll: surface
    worker/feeder tracebacks from the error queue (peek-and-put-back, same
    protocol as node._check_errors, parity TFSparkNode.py:508-515), honor a
    hub moved to ``terminating``/``stopped``, and give up after
    ``liveness_timeout`` seconds without data.
    """
    from tensorflowonspark_tpu.node import _check_errors
    try:
      self._check_liveness_inner(stalled_since, _check_errors)
    except BaseException:
      # the feed is being abandoned via this raise: retire the fetch
      # thread NOW or it keeps polling (and eagerly acking) the hub
      # forever — racing any replacement DataFeed for chunks it would
      # then bury in its dead queue
      self._stop_pipeline()
      raise

  def _check_liveness_inner(self, stalled_since: float,
                            _check_errors) -> None:
    _check_errors(self.hub, "next_batch")
    try:
      state = self.hub.get("state")
    except Exception:  # noqa: BLE001 - hub manager itself may be gone
      raise FeedStalledError("feed hub is unreachable from next_batch — "
                             "the node's manager process died")
    if state in ("terminating", "stopped"):
      logger.info("hub state %r during next_batch; stopping feed", state)
      self.done_feeding = True
      return
    if (self.liveness_timeout is not None
        and time.monotonic() - stalled_since > self.liveness_timeout):
      raise FeedStalledError(
          "no data and no end-of-feed marker for %.0fs (hub state %r) — "
          "feeder presumed dead" % (self.liveness_timeout, state))

  # -- batch assembly --------------------------------------------------------

  def _assemble_columns(self, batch_size: int, dtype=None,
                        require_single: bool = False):
    """Columnar fast path: a batch as a list of column arrays, or None.

    Plans up to ``batch_size`` rows over PENDING chunks first (fetching
    more as needed), committing nothing until the whole batch is known to
    be assemblable from ColumnChunks with matching schemas — any
    heterogeneous/legacy row chunk in the stretch returns None and the
    untouched buffer falls back to the row path. Markers keep their exact
    row-path semantics: end-of-feed ends the batch (partial OK) and sets
    ``done_feeding``; ``EndPartition`` is skipped in train mode and ends
    the batch in inference mode. Each output column is ONE
    ``np.concatenate`` over chunk slices — the only copy on the path —
    and an ALIGNED batch (the whole stretch inside one chunk) skips even
    that: the column slices hand out directly as READ-ONLY zero-copy
    views of the decoded chunk (``stats["aligned_batches"]`` counts
    them). Callers must treat batch arrays as immutable on that path —
    the views share the chunk's buffer with sibling batches.
    """
    import numpy as np
    plan = []             # (ColumnChunk, start, stop)
    pops = 0              # buffer entries fully consumed, in order
    tail_off = None       # new offset for a partially-consumed head chunk
    end_of_feed = False
    need = batch_size
    sig = None            # (ncols, per-col (dtype, trailing shape))
    stalled_since = time.monotonic()
    while need > 0:
      if pops >= len(self._chunks):
        if self.done_feeding:
          break
        if not self._fetch(1.0):
          if not self.done_feeding:
            self._check_liveness(stalled_since)
          continue
        stalled_since = time.monotonic()
        continue
      entry = self._chunks[pops]
      kind = entry[0]
      if kind == "rows":
        return None
      if kind == "marker":
        m = entry[1]
        if m is None:
          end_of_feed = True
          pops += 1
          break
        if self.train_mode:
          pops += 1
          continue
        if not plan:
          # partition boundary with ZERO planned rows: leave the marker
          # (nothing was committed) so the row fallback pops it and
          # returns the same empty boundary batch the row path always
          # produced when batch_size exactly divides the partition
          return None
        pops += 1
        break  # inference: batch ends at the partition boundary
      cc, off = entry[1], entry[2]
      if require_single and (cc.tuples or len(cc.cols) != 1):
        return None
      this_sig = (len(cc.cols),
                  tuple((a.dtype.str, a.shape[1:]) for a in cc.cols))
      if sig is None:
        sig = this_sig
      elif this_sig != sig:
        return None  # schema changed mid-batch: row fallback handles it
      take = min(need, cc.n - off)
      plan.append((cc, off, off + take))
      need -= take
      if off + take >= cc.n:
        pops += 1
        tail_off = None
      else:
        tail_off = off + take
        break  # batch filled from a partial chunk

    if not plan:
      # nothing columnar to hand out; commit marker effects and fall back
      for _ in range(pops):
        self._chunks.popleft()
      if end_of_feed:
        logger.info("end-of-feed marker received")
        self.done_feeding = True
      return None

    t0 = time.perf_counter()
    for _ in range(pops):
      self._chunks.popleft()
    if tail_off is not None:
      self._chunks[0][2] = tail_off
    if end_of_feed:
      logger.info("end-of-feed marker received")
      self.done_feeding = True
    ncols = len(plan[0][0].cols)
    if self.input_tensors is not None:
      ncols = min(ncols, len(self.input_tensors))
    out = []
    aligned = len(plan) == 1
    for j in range(ncols):
      if aligned:
        # aligned fast path: the whole batch sits inside one chunk, so
        # the slice IS the column — a zero-copy read-only view (safe to
        # hand out: the decoded chunk's buffer is msgpack-owned bytes,
        # never a transport scratch buffer)
        cc, a, b = plan[0]
        arr = cc.cols[j][a:b]
      else:
        pieces = [cc.cols[j][a:b] for cc, a, b in plan]
        arr = np.concatenate(pieces)  # the hand-off copy
      if dtype is not None and arr.dtype != np.dtype(dtype):
        arr = arr.astype(dtype)
      out.append(arr)
    if aligned:
      self.stats["aligned_batches"] += 1
    self.stats["assemble_s"] += time.perf_counter() - t0
    return out

  def _next_rows(self, batch_size: int) -> List:
    """Row-granular batch loop (the legacy semantics, unchanged)."""
    batch: List = []
    stalled_since = time.monotonic()
    while len(batch) < batch_size:
      if not self._chunks:
        if self.done_feeding:
          break
        if not self._fetch(1.0):
          if self.done_feeding:
            break
          self._check_liveness(stalled_since)
          continue
        stalled_since = time.monotonic()
        continue
      entry = self._chunks[0]
      kind = entry[0]
      if kind == "marker":
        self._chunks.popleft()
        m = entry[1]
        if m is None:
          logger.info("end-of-feed marker received")
          self.done_feeding = True
          break
        if self.train_mode:
          continue
        break  # inference: batch ends at the partition boundary
      if kind == "cols":
        # row-list consumers materialize the chunk (same per-row cost the
        # old decode paid eagerly for every chunk)
        self._chunks[0] = ("rows",
                           collections.deque(entry[1].rows(entry[2])))
        continue
      rows = entry[1]
      stop = False
      while rows and len(batch) < batch_size:
        item = rows.popleft()
        if item is None:
          logger.info("end-of-feed marker received")
          self.done_feeding = True
          stop = True
          break
        if isinstance(item, (Marker, EndPartition)):
          if self.train_mode:
            continue
          stop = True  # inference: batch ends at the partition boundary
          break
        batch.append(item)
      if not rows:
        self._chunks.popleft()
      if stop:
        break
    return batch

  def next_batch(self, batch_size: int):
    """Return up to ``batch_size`` items (or a dict of columns when an
    input_mapping is configured). Blocks until data arrives.

    With an input_mapping, homogeneous array chunks take the columnar
    fast path and the dict values are stacked ndarrays; heterogeneous /
    legacy row chunks keep the historical list values. The plain row-list
    form (no mapping) is unchanged.

    Raises :class:`FeedStalledError` (or the worker's own error, re-raised
    from the error queue) instead of blocking forever when the producer
    side has died; see ``liveness_timeout``.
    """
    if self._rec is None and self._obs_m is None:
      return self._next_batch_impl(batch_size)
    t0 = time.monotonic()
    out = self._next_batch_impl(batch_size)
    if isinstance(out, dict):
      n = len(next(iter(out.values()))) if out else 0
    else:
      n = len(out)
    self._obs_batch(t0, n)
    return out

  def _next_batch_impl(self, batch_size: int):
    if self.input_tensors is not None:
      cols = self._assemble_columns(batch_size)
      if cols is not None:
        return dict(zip(self.input_tensors, cols))
    batch = self._next_rows(batch_size)
    if self.input_tensors is None:
      return batch
    # transpose rows -> named columns
    cols: Dict[str, List] = {name: [] for name in self.input_tensors}
    for row in batch:
      for name, value in zip(self.input_tensors, row):
        cols[name].append(value)
    return cols

  def should_stop(self) -> bool:
    """True once the end-of-feed marker was consumed (parity :303-305)."""
    return self.done_feeding

  def batch_results(self, results: Sequence,
                    timeout: Optional[float] = None) -> None:
    """Push a batch of inference results (parity :307-318).

    Bounded (TOS001): the push blocks at most ``timeout`` seconds
    (default: this feed's ``liveness_timeout``). An unbounded put here
    wedged the node forever when the inference collector died — the
    worker kept its executor busy and a pinned relaunch could never
    schedule behind it (the PR 1 slot-deadlock class).
    """
    timeout = timeout if timeout is not None else self.liveness_timeout
    try:
      self._queue_out.put_many(list(results), block=True, timeout=timeout)
    except Exception as e:  # noqa: BLE001 - recast ONLY the queue-full
      # timeout (which may arrive as a proxy-re-raised feedhub.QueueFull)
      if type(e).__name__ != "QueueFull":
        raise
      admitted = getattr(e, "admitted", 0)
      err = FeedStalledError(
          "output queue still full after %.0fs pushing %d result(s) (%d "
          "already enqueued — skip them on retry) — the inference collector "
          "is presumed dead" % (timeout or 0, len(results), admitted))
      # a timed-out put_many may have enqueued a prefix; callers that retry
      # must resume at results[admitted:] or they double-deliver
      err.admitted = admitted
      raise err from e

  def terminate(self, settle_rounds: int = 3,
                settle_timeout: float = 0.1) -> None:
    """Request early termination: mark the hub terminating and drain the
    input queue so blocked feeders can finish (parity :320-343).

    The drain settles after ``settle_rounds`` consecutive empty polls of
    ``settle_timeout`` seconds each — an already-empty queue costs
    ``settle_rounds * settle_timeout`` (0.3 s at the defaults), not the
    3 s the old fixed 1-second polls burned on every teardown."""
    logger.info("terminate() requested; draining input queue")
    self.hub.set("state", "terminating")
    self.done_feeding = True
    self._stop_pipeline()  # buffered chunks were already acked; discard
    self._chunks.clear()
    empty_rounds = 0
    while empty_rounds < settle_rounds:
      got = self._queue_in.get_chunk(DEFAULT_FETCH_ROWS, block=True,
                                     timeout=settle_timeout)
      if got:
        self._queue_in.task_done(_chunk_weight(got))
        empty_rounds = 0
      else:
        empty_rounds += 1

  def next_batch_synced(self, batch_size: int):
    """``next_batch`` with global step agreement across jax processes.

    Synchronous SPMD training deadlocks if one worker's feed runs dry while
    others enter a collective. Before handing out a batch, all processes
    vote "I have a full batch"; if anyone is short, EVERY process stops
    (returning a batch signalling stop via ``should_stop()``). At most one
    partial batch per worker is discarded at end-of-data — the principled
    replacement for the reference's train-90%-of-steps workaround
    (examples/mnist/keras/mnist_spark.py:58-64).
    """
    from tensorflowonspark_tpu.parallel.collectives import \
        all_processes_agree
    batch = self.next_batch(batch_size)
    n = len(batch[self.input_tensors[0]]) if isinstance(batch, dict) \
        else len(batch)
    ok = n == batch_size and not self.done_feeding
    if not all_processes_agree(ok):
      self.done_feeding = True
      return {k: [] for k in batch} if isinstance(batch, dict) else []
    return batch

  # -- TPU staging -----------------------------------------------------------

  def next_batch_arrays(self, batch_size: int, dtype=None):
    """Like ``next_batch`` but returns stacked numpy arrays, ready for
    ``jax.device_put`` (the host-staging step of the feed plane).

    Columnar chunks assemble with NO per-row loop: one concatenate of
    column views per output column (single-column chunks without an
    input_mapping return one array; with a mapping, a dict of arrays).
    Row/heterogeneous chunks fall back to the historical stack."""
    import numpy as np
    obs_on = self._rec is not None or self._obs_m is not None
    t0 = time.monotonic() if obs_on else 0.0
    cols = self._assemble_columns(
        batch_size, dtype=dtype, require_single=self.input_tensors is None)
    if cols is not None:
      if obs_on:
        self._obs_batch(t0, len(cols[0]))
      if self.input_tensors is None:
        return cols[0]
      return dict(zip(self.input_tensors, cols))
    # the row fallback delegates to next_batch, which records its own
    # obs batch — no double counting
    batch = self.next_batch(batch_size)
    if isinstance(batch, dict):
      return {k: np.asarray(v, dtype=dtype) for k, v in batch.items()}
    return np.asarray(batch, dtype=dtype)

  def next_slab_arrays(self, batch_size: int, unroll: int, dtype=None):
    """``unroll`` batches assembled as ONE ``[unroll, batch_size, ...]``
    slab — the chunk-buffer source of the fused train loop.

    One ``next_batch_arrays(batch_size * unroll)`` call plans the whole
    stretch over the chunk buffer (still a single concatenate per
    column; markers keep their exact per-batch semantics — train mode
    skips ``EndPartition`` inside a slab exactly like per-batch
    assembly does), and a full stretch reshapes for free into the slab
    (``data.readers.Slab``). A SHORT stretch (end-of-feed, or an
    inference-mode partition boundary) returns the flat arrays
    unchanged, exactly as ``next_batch_arrays`` would — the caller
    (``data.readers.slab_batches``) splits them back into per-step
    batches so batch order matches the per-step path bit for bit.
    """
    from tensorflowonspark_tpu.data.readers import Slab
    if unroll <= 1:
      return self.next_batch_arrays(batch_size, dtype=dtype)
    want = batch_size * unroll
    got = self.next_batch_arrays(want, dtype=dtype)

    def _rows(x):
      if isinstance(x, dict):
        return len(next(iter(x.values()))) if x else 0
      return len(x)

    def _stack(arr):
      # reshape of the freshly-concatenated (contiguous) column: no copy
      return arr.reshape((unroll, batch_size) + arr.shape[1:])

    if _rows(got) != want:
      return got
    if isinstance(got, dict):
      return Slab({k: _stack(v) for k, v in got.items()})
    return Slab(_stack(got))


def drain_pending_rows(hub, qname: str = "input", settle_rounds: int = 3,
                       settle_timeout: float = 0.1,
                       keep_markers: bool = False) -> List:
  """Pull every undelivered row out of a (presumed dead) node's feed queue.

  Fault-recovery primitive: when a worker dies mid-feed, rows already
  pushed into its hub queue would otherwise be lost — and the feeder tasks
  blocked in ``queue.join()`` would wedge until their feed timeout. This
  drains the queue chunk by chunk (expanding codec envelopes back into
  rows), acking each unit with ``task_done`` so blocked feeders complete,
  and returns the data rows for requeueing through the engine feed path
  (``ClusterSupervisor`` refeeds them to live workers).

  End-of-feed ``None`` markers are always dropped: the requeued rows ride
  a fresh feed round with its own end-of-feed. ``EndPartition`` (and any
  other ``Marker``) is dropped by default but PRESERVED in stream order
  with ``keep_markers=True`` — inference feeds need the partition
  boundaries to keep per-partition result alignment across a refeed (the
  supervisor passes this for inference recovery). The drain keeps
  sweeping until ``settle_rounds`` consecutive empty polls, catching a
  feeder caught mid-put.

  Only call this against a hub whose consumer is KNOWN dead — draining a
  live node's queue steals its input.
  """
  queue = hub.get_queue(qname)
  rows: List = []
  empty = 0
  while empty < settle_rounds:
    got = queue.get_chunk(DEFAULT_FETCH_ROWS, block=True,
                          timeout=settle_timeout)
    if not got:
      empty += 1
      continue
    empty = 0
    queue.task_done(_chunk_weight(got))
    kind = got[0]
    if kind == "marker":
      if keep_markers and got[1] is not None:
        rows.append(got[1])
      continue
    if kind == "enc":
      ckind, decoded = chunkcodec.classify_decoded(
          chunkcodec.decode_columns(got[2]))
      if ckind == "marker":
        items = [decoded]
      elif isinstance(decoded, chunkcodec.ColumnChunk):
        items = decoded.rows()
      else:
        items = decoded
    else:  # "rows"
      items = got[1]
    rows.extend(r for r in items
                if r is not None
                and (keep_markers or not isinstance(r, Marker)))
  return rows


def prefetch_to_device(batches, size: int = 2, device=None):
  """Overlap host→device staging with device compute.

  Wraps an iterator of host batches (numpy arrays / pytrees of them) and
  yields device-resident batches, keeping up to ``size`` transfers in
  flight: ``jax.device_put`` is asynchronous, so batch N+1's PCIe/ICI
  transfer runs while the caller's jitted step for batch N executes —
  the standard TPU input-pipeline trick, packaged for DataFeed loops::

      def host_batches():
          while not feed.should_stop():
              b = feed.next_batch_arrays(B)
              if len(b):           # [] after the end-of-feed marker
                  yield b
      for x in prefetch_to_device(host_batches(), size=2):
          state, loss = step(state, x)

  (or use ``data.readers.feed_batches(feed, B)`` for the loop above).
  With ``size=1`` this degrades to plain ``device_put`` per batch. The
  buffer holds ``size`` batches in device memory — keep it small. Note
  the fill also gates startup: the first batch is yielded only once
  ``size`` batches have staged (or the source ends), so a large ``size``
  on a slow feed delays step 0 by ``size`` batch-fetches.
  Delegates to ``data.readers.device_prefetch`` — the FILES-mode input
  pipeline's prefetcher — so there is exactly ONE implementation of the
  overlap trick (``device`` may also be a sharding for SPMD staging).
  Stacked with the feed's own fetch pipeline (``TOS_FEED_PIPELINE``),
  the three stages overlap: hub RPC + decode (fetch thread), host→device
  transfer (this buffer), and the jitted step.
  """
  from tensorflowonspark_tpu.data.readers import device_prefetch
  return device_prefetch(batches, size=size, sharding=device)
