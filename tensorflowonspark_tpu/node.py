"""L2' per-executor node runtime.

Capability parity with the reference's ``TFSparkNode.py``
(/root/reference/tensorflowonspark/TFSparkNode.py), re-designed for TPU:

- device allocation exports TPU chip shares (utils.tpu_info) instead of
  ``CUDA_VISIBLE_DEVICES`` from nvidia-smi parsing (reference :179-239);
- the synthesized cluster spec feeds ``jax.distributed.initialize`` (the JAX
  analog of exporting ``TF_CONFIG``, reference :373-384) — collectives then
  compile to XLA all-reduce over ICI/DCN rather than TF gRPC;
- roles: workers run the user main fn in the foreground (FILES input mode) or
  a background process (ENGINE/SPARK input mode, reference :431-439);
  ps/evaluator run it in a background process while the foreground blocks on a
  ``control`` queue until the driver sends ``None`` (reference :441-458);
- fault propagation parity: a dedicated ``error`` queue per executor;
  background exceptions captured as tracebacks (reference :423-429), re-raised
  at shutdown with peek-and-put-back so engine task retries still observe the
  failure (reference :644-650);
- retried bring-up tasks re-register idempotently, while a live hub from a
  concurrent duplicate forces an error (reference :259-265).
"""

import logging
import os
import socket
import subprocess
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

from tensorflowonspark_tpu.control import feedhub, rendezvous
from tensorflowonspark_tpu.utils import hostinfo, paths, tpu_info

logger = logging.getLogger(__name__)

JAX_ROLES = ("chief", "master", "worker")  # roles that join the JAX mesh
BACKGROUND_ROLES = ("ps", "evaluator")     # roles parked on a control queue

HUB_ADDR_FILE = "hub_addr"

#: pins the per-node coordinator/collectives port (env registry: TOS008)
ENV_NODE_PORT = "TOS_TPU_NODE_PORT"

#: directory for JAX's persistent compilation cache, applied at node
#: bring-up in whichever process runs the user fn — relaunched/persistent
#: executors then LOAD their jitted programs instead of recompiling them
#: (cache hits are surfaced as ``xla.cache_hits``, never counted as
#: fresh compiles — obs/device.py). Unset = no persistent cache.
#: (env registry: TOS008)
ENV_COMPILE_CACHE = "TOS_COMPILE_CACHE"

#: feeder byte budget per wire envelope: when set (> 0), feeders size
#: chunks adaptively from observed encoded bytes/row instead of the fixed
#: ``feed_chunk_size`` row count — small rows stop paying per-envelope
#: manager round-trips, fat rows stop ping-ponging off ``MAX_PAYLOAD``
#: splits. ``cluster.run(feed_target_bytes=...)`` takes precedence over
#: the env. 0/unset = fixed row count. (env registry: TOS008)
ENV_FEED_TARGET_BYTES = "TOS_FEED_TARGET_BYTES"

#: adaptive-sizing row-count clamp, both directions: an envelope never
#: carries fewer rows than the floor (per-envelope overhead would
#: dominate) nor more than the cap (consumer-side latency + memory)
_ADAPT_MIN_ROWS = 16
_ADAPT_MAX_ROWS = 8192


def _setup_compile_cache() -> bool:
  """Point JAX's persistent compilation cache at ``TOS_COMPILE_CACHE``.

  Called at node bring-up in the process that runs the user main fn
  (both the foreground FILES-mode path and the spawned background
  runner) BEFORE any jit. Zero work — and no jax import — when the env
  is unset, so feeder tasks and bare executors never pay it. The
  min-compile-time / min-entry-size floors drop to 0 so even the small
  CPU-harness programs cache: the knob's whole point is that a
  supervised relaunch (or the next run of a persistent executor) skips
  its recompiles.
  """
  cache_dir = os.environ.get(ENV_COMPILE_CACHE)
  if not cache_dir:
    return False
  try:
    os.makedirs(cache_dir, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
      try:
        jax.config.update(knob, val)
      except Exception:  # noqa: BLE001 - knob renamed on this jax
        pass
    logger.info("persistent compilation cache at %s", cache_dir)
    return True
  except Exception as e:  # noqa: BLE001 - a broken cache dir must not
    # fail bring-up; the node just compiles as before
    logger.warning("compilation cache setup failed (%s); continuing "
                   "without it", e)
    return False


#: env values _apply_node_env exported in THIS (persistent) executor
#: process — so a later cluster that sets nothing can retract exactly
#: what a previous cluster exported, while a user's own env pin (a value
#: we never wrote) still passes through
_applied_node_env: Dict[str, str] = {}


def _apply_node_env(meta: dict) -> None:
  """Export cluster-level training knobs into this node process's env.

  ``cluster.run(train_unroll=K)`` rides the cluster meta so EVERY node —
  foreground or spawned background runner (which inherits this env at
  spawn) — sees the same ``TOS_TRAIN_UNROLL``, which
  ``parallel.sharding.resolve_unroll`` (and thus
  ``make_train_loop``/``slab_batches``) reads as its default. An
  explicit cluster value wins over a stale env; when the cluster sets
  nothing, an export left behind by a PREVIOUS cluster on this
  persistent executor is retracted (or run B would silently fuse with
  run A's K), while a user-set env pin passes through.
  """
  from tensorflowonspark_tpu.parallel.sharding import ENV_TRAIN_UNROLL
  unroll = meta.get("train_unroll")
  if unroll:
    _applied_node_env[ENV_TRAIN_UNROLL] = str(int(unroll))
    os.environ[ENV_TRAIN_UNROLL] = _applied_node_env[ENV_TRAIN_UNROLL]
  elif _applied_node_env.get(ENV_TRAIN_UNROLL) is not None \
      and os.environ.get(ENV_TRAIN_UNROLL) == \
      _applied_node_env[ENV_TRAIN_UNROLL]:
    os.environ.pop(ENV_TRAIN_UNROLL, None)
    _applied_node_env.pop(ENV_TRAIN_UNROLL)


class TPUNodeContext(object):
  """Per-node metadata handed to the user main fn as ``ctx``.

  Field parity with the reference's TFNodeContext (TFSparkNode.py:62-108),
  plus the TPU-native coordinates (``coordinator_address``, ``process_id``,
  ``num_processes``) needed for ``jax.distributed.initialize``.
  """

  def __init__(self, executor_id=0, job_name="worker", task_index=0,
               cluster_spec=None, default_fs="file://", working_dir=".",
               hub=None, tmp_socket=None, coordinator_address=None,
               process_id=0, num_processes=1, cluster_info=None,
               restart_count=0, heartbeat=None):
    self.executor_id = executor_id
    self.worker_num = executor_id          # backwards-compat alias
    self.job_name = job_name
    self.task_index = task_index
    self.cluster_spec = cluster_spec or {}
    self.num_workers = sum(
        len(v) for k, v in self.cluster_spec.items() if k in JAX_ROLES)
    self.default_fs = default_fs
    self.defaultFS = default_fs            # backwards-compat alias
    self.working_dir = working_dir
    self.mgr = hub                         # backwards-compat alias
    self.hub = hub
    self.tmp_socket = tmp_socket
    self.coordinator_address = coordinator_address
    self.process_id = process_id
    self.num_processes = num_processes
    self.cluster_info = cluster_info or []
    #: how many times the supervisor relaunched this node (0 = first
    #: launch). A relaunched node should resume from its latest
    #: checkpoint: ``state, start = ctx.checkpoint_manager(d).restore_or(state)``
    self.restart_count = restart_count
    self._heartbeat = heartbeat

  # -- convenience mirrors (parity: TFSparkNode.py:92-108) -------------------

  def absolute_path(self, path: str) -> str:
    return paths.absolute_path(path, self.default_fs, self.working_dir)

  def get_data_feed(self, train_mode=True, qname_in="input",
                    qname_out="output", input_mapping=None,
                    liveness_timeout=600.0):
    from tensorflowonspark_tpu.datafeed import DataFeed
    return DataFeed(self.hub, train_mode, qname_in, qname_out, input_mapping,
                    liveness_timeout=liveness_timeout)

  def release_port(self) -> None:
    """Release the reserved coordinator port prior to starting JAX distributed
    (parity: TFNode.release_port, TFNode.py:214-221)."""
    if self.tmp_socket is not None:
      self.tmp_socket.close()
      self.tmp_socket = None

  def export_model(self, state, export_dir: str) -> str:
    from tensorflowonspark_tpu.utils import compat
    return compat.export_model(state, export_dir, self.is_chief)

  @property
  def is_chief(self) -> bool:
    return is_chief(self.job_name, self.task_index, self.cluster_spec)

  @property
  def is_restart(self) -> bool:
    """True when this node is a supervised relaunch of a dead predecessor."""
    return self.restart_count > 0

  def checkpoint_manager(self, directory: str, **kwargs):
    """A :class:`utils.checkpoint.CheckpointManager` for this node — the
    preemption-safe resume hook: ``state, start_step = mgr.restore_or(state)``
    continues a relaunched node from its latest checkpoint (``start_step``
    is 0 on a fresh launch)."""
    from tensorflowonspark_tpu.utils.checkpoint import CheckpointManager
    return CheckpointManager(directory, **kwargs)

  def report_progress(self, value) -> None:
    """Attach an application progress value (e.g. the training step) to
    this node's heartbeats — visible driver-side via the HEALTH verb."""
    if self._heartbeat is not None:
      self._heartbeat.set_progress(value)

  def initialize_distributed(self) -> None:
    """Join the JAX process group (TPU analog of TF reading TF_CONFIG).

    Safe to skip for single-process clusters. ps/evaluator nodes never call
    this — they are outside the mesh.
    """
    if self.num_processes <= 1:
      logger.info("single-process cluster; skipping jax.distributed")
      return
    self.release_port()
    import jax
    try:
      # CPU backends need an explicit cross-process collectives transport;
      # on TPU this knob doesn't exist and collectives ride ICI natively
      jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - unknown config name on this backend
      pass
    logger.info("joining jax process group: coordinator=%s rank=%d/%d",
                self.coordinator_address, self.process_id,
                self.num_processes)
    jax.distributed.initialize(
        coordinator_address=self.coordinator_address,
        num_processes=self.num_processes,
        process_id=self.process_id)


def is_chief(job_name: str, task_index: int, roles) -> bool:
  """Chief = the chief/master node, or worker:0 when no chief exists.

  ``roles`` is any container of job names (cluster spec or template).
  """
  return (job_name in ("chief", "master")
          or (job_name == "worker" and task_index == 0
              and not any(r in roles for r in ("chief", "master"))))


def _role_of(executor_id: int, cluster_template: Dict[str, List[int]]):
  for job_name, ids in cluster_template.items():
    if executor_id in ids:
      return job_name, ids.index(executor_id)
  raise ValueError("executor %d not present in cluster template %r"
                   % (executor_id, cluster_template))


def _jax_process_table(cluster_info: List[dict]):
  """Rank the mesh-joining nodes: chief/master first, then workers by index.

  Returns (ordered list of node metas, coordinator host:port).
  """
  chiefs = [n for n in cluster_info if n["job_name"] in ("chief", "master")]
  workers = sorted((n for n in cluster_info if n["job_name"] == "worker"),
                   key=lambda n: n["task_index"])
  table = chiefs + workers
  coord = "%s:%d" % (table[0]["host"], table[0]["port"]) if table else None
  return table, coord


def _build_cluster_spec(cluster_info: List[dict]) -> Dict[str, List[str]]:
  """{job_name: ["host:port", ...]} sorted by task index.

  Rejects duplicate executor ids (parity: TFSparkNode.py:50-53).
  """
  seen = set()
  for n in cluster_info:
    if n["executor_id"] in seen:
      raise RuntimeError("duplicate executor_id %d in cluster info"
                         % n["executor_id"])
    seen.add(n["executor_id"])
  spec: Dict[str, List[str]] = {}
  by_job: Dict[str, List[dict]] = {}
  for n in cluster_info:
    by_job.setdefault(n["job_name"], []).append(n)
  for job, nodes in by_job.items():
    spec[job] = ["%s:%d" % (n["host"], n["port"])
                 for n in sorted(nodes, key=lambda n: n["task_index"])]
  return spec


def _find_tensorboard(search_path: Optional[str] = None):
  """Locate a TensorBoard entry point, or False.

  Searches PATH, the python bin dir, sys.path and PYTHONPATH for the
  ``tensorboard`` executable, then for the module form ``tensorboard/main.py``
  (parity: the reference's three-step search, TFSparkNode.py:310-322 —
  reordered so an explicit PATH entry OVERRIDES the interpreter's bin dir,
  the conventional Unix precedence; a container may carry a stub
  ``tensorboard`` launcher next to python that shadows the real one).
  """
  if search_path is None:
    search_path = os.pathsep.join([
        os.environ.get("PATH", ""),
        os.path.dirname(sys.executable),
        os.pathsep.join(p for p in sys.path if p),
        os.environ.get("PYTHONPATH", ""),
    ])
  return hostinfo.find_in_path(search_path, "tensorboard") or \
      hostinfo.find_in_path(search_path,
                            os.path.join("tensorboard", "main.py"))


def _spawn_tensorboard(log_dir: str) -> Optional[dict]:
  """Launch a TensorBoard server subprocess (parity: TFSparkNode.py:292-329).

  Port selection: env ``TENSORBOARD_PORT`` or an ephemeral bind. Returns
  {'pid','url'} or None when no tensorboard entry point is found on the
  python bin dir / PATH / sys.path / PYTHONPATH.
  """
  tb_port = os.environ.get("TENSORBOARD_PORT")
  port = int(tb_port) if tb_port else hostinfo.get_free_port()
  tb_bin = _find_tensorboard()
  if not tb_bin:
    logger.warning("tensorboard not found on PATH/PYTHONPATH; skipping "
                   "launch")
    return None
  proc = subprocess.Popen(
      [sys.executable, tb_bin, "--logdir", log_dir, "--port", str(port),
       "--host", "0.0.0.0"],
      stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
  url = "http://%s:%d" % (hostinfo.get_ip_address(), port)
  logger.info("started TensorBoard pid=%d at %s", proc.pid, url)
  return {"pid": proc.pid, "url": url}


def _start_obs_shipper(server_addr, executor_id: int, sender):
  """Executor-side obs plane bring-up (None when ``TOS_OBS`` is off).

  The shipper shares the HeartbeatSender's clock estimator — the BEAT
  round-trip is the TIME exchange — so span timestamps anchor to the
  driver's monotonic clock without extra control-plane traffic; the
  process recorder adopts the same estimator for its JSONL exports.
  """
  from tensorflowonspark_tpu.obs import metrics as obs_metrics
  if not (obs_metrics.enabled() and server_addr):
    return None
  from tensorflowonspark_tpu.obs import collector as obs_collector
  from tensorflowonspark_tpu.obs import device as obs_device
  from tensorflowonspark_tpu.obs import spans as obs_spans
  clock = sender.clock if sender is not None else None
  rec = obs_spans.active()
  if rec is not None and clock is not None:
    rec.clock = clock
  shipper = obs_collector.ObsShipper(tuple(server_addr), executor_id,
                                     clock=clock, label="exec")
  # compile/device tier: jax.monitoring recompile sentinel + a device-
  # memory sampler on the shipper cadence, so compile counts and memory
  # watermarks ride the normal OBS wire to the driver's detector loop
  obs_device.install(shipper)
  return shipper.start()


# feeder-task obs shipper: one per executor PROCESS, shared across feed
# tasks (they are too short-lived to each own a thread + socket)
_feeder_shipper = None
_feeder_shipper_addr = None
_feeder_shipper_lock = threading.Lock()


def _ensure_feeder_shipper(server_addr, executor_id: int):
  """Obs shipper for feeder tasks (None when ``TOS_OBS`` is off).

  ENGINE-mode feed tasks run in the engine's executor process, which
  hosts no node runtime — the node's shipper
  (:func:`_start_obs_shipper`) lives in the background-runner process.
  Without a shipper HERE, the feeder-side wire counters
  (``feed.wire_bytes``/``feed.wire_rows``/``feed.wire_enc.*``) stay
  process-local and never reach the driver's sink. Cached across feed
  tasks; re-pointed when a new cluster (fresh rendezvous server) reuses
  a persistent executor process. The sink merges metric deltas
  additively per executor id, so this coexists with the node's shipper
  (the feeder process owns a disjoint metric set)."""
  global _feeder_shipper, _feeder_shipper_addr
  from tensorflowonspark_tpu.obs import metrics as obs_metrics
  if not (obs_metrics.enabled() and server_addr):
    return None
  addr = (server_addr[0], int(server_addr[1]))
  with _feeder_shipper_lock:
    if _feeder_shipper is not None and _feeder_shipper_addr == addr:
      return _feeder_shipper
    if _feeder_shipper is not None:
      _feeder_shipper.stop(timeout=1.0)
    from tensorflowonspark_tpu.obs import collector as obs_collector
    shipper = obs_collector.ObsShipper(addr, executor_id,
                                       label="feeder").start()
    _feeder_shipper = shipper
    _feeder_shipper_addr = addr
    return shipper


def _background_runner(fn_bytes: bytes, tf_args, ctx_kwargs: dict,
                       hub_addr, authkey: bytes, server_addr=None,
                       heartbeat_interval=None):
  """Entry point of the background process running the user main fn.

  Reconnects to this executor's feed hub by address (the hub lives in a
  separate manager process), captures any exception into the ``error`` queue
  as a traceback (parity: TFSparkNode.py:423-429) and drives the hub state
  machine to ``'stopped'``. Heartbeats run HERE — in the process executing
  the user fn — so a SIGKILL/OOM of this process stops the beats and the
  driver's supervisor declares the node dead.
  """
  import cloudpickle
  # the background runner is the process that jits: point JAX's
  # persistent compilation cache (TOS_COMPILE_CACHE) here, before the
  # user fn's first compile
  _setup_compile_cache()
  hub = feedhub.connect(tuple(hub_addr), authkey)
  sender = None
  if server_addr and heartbeat_interval:
    sender = rendezvous.HeartbeatSender(
        tuple(server_addr), ctx_kwargs["executor_id"],
        interval=heartbeat_interval).start()
  shipper = _start_obs_shipper(server_addr, ctx_kwargs["executor_id"],
                               sender)
  ctx = TPUNodeContext(hub=hub, heartbeat=sender, **ctx_kwargs)
  try:
    fn = cloudpickle.loads(fn_bytes)
    fn(tf_args, ctx)
  except BaseException:  # noqa: BLE001 - traceback must reach the driver
    tb = traceback.format_exc()
    logger.error("background main fn failed:\n%s", tb)
    try:
      hub.get_queue("error").put(tb)
    except Exception:  # noqa: BLE001 - error queue unreachable: fall back
      # so the failure still reaches the driver instead of vanishing with
      # this process (TOS004 — traceback propagation is the contract)
      try:
        hub.set("last_error", tb)   # the kv store may outlive queue breakage
      except Exception:  # noqa: BLE001 - hub manager fully gone; the
        # executor's inherited stderr is the last channel that still works
        os.write(2, ("background main fn failed:\n%s" % tb).encode())
  finally:
    if shipper is not None:
      shipper.stop()           # final delta flush + JSONL close first,
    if sender is not None:     # so the driver hears it before the bye
      sender.stop()
    try:
      hub.set("state", "stopped")
    except Exception:  # noqa: BLE001
      pass


def make_node_fn(main_fn, tf_args, cluster_meta: dict):
  """Build the engine task that brings up one cluster node (parity:
  TFSparkNode.run → _mapfn, TFSparkNode.py:158-465)."""
  import cloudpickle
  fn_bytes = cloudpickle.dumps(main_fn)

  def _mapfn(iterator):
    # 1. learn this task's executor id from its partition (parity :176-177).
    # A supervised relaunch hands a dict payload carrying the restart count
    # (cluster.ClusterSupervisor → Engine.relaunch_task).
    payload = next(iter(iterator))
    if isinstance(payload, dict):
      executor_id = payload["executor_id"]
      restart_count = int(payload.get("restart", 0))
    else:
      executor_id = payload
      restart_count = 0
    meta = cluster_meta
    working_dir = os.getcwd()
    job_name, task_index = _role_of(executor_id, meta["cluster_template"])
    authkey = meta["authkey"] if isinstance(meta["authkey"], bytes) \
        else bytes(meta["authkey"])

    # 2. duplicate/stale hub detection (parity :259-265): a hub in this
    # working dir that answers with our authkey and reports itself live means
    # another concurrent node task (same cluster) owns this executor — fail
    # so the engine retries elsewhere. Anything else (dead socket, stale
    # 'stopped' hub, or an AuthenticationError from a *previous* cluster's
    # hub with a different key) is reclaimed, releasing the old manager.
    reclaimed = os.path.exists(os.path.join(working_dir, HUB_ADDR_FILE))
    if reclaimed:
      old = None
      try:
        with open(os.path.join(working_dir, HUB_ADDR_FILE)) as f:
          host, port = f.read().strip().split(":")
        old = feedhub.connect((host, int(port)), authkey)
        state = old.get("state")
        if state in ("running", "terminating"):
          raise RuntimeError(
              "executor already runs a live node (hub state=%r); failing this "
              "task so the engine can retry it elsewhere" % state)
        logger.info("found stale hub (state=%r); reclaiming executor", state)
        # a SIGKILLed predecessor leaves its hub manager as a live orphan
        # (the supervisor marks it 'dead' after draining); reap it so
        # managers don't pile up across relaunches
        try:
          old.force_exit()
        except Exception:  # noqa: BLE001 - manager already gone
          pass
      except RuntimeError:
        raise
      except Exception as e:  # noqa: BLE001 - dead/foreign hub -> reclaim
        logger.info("found unreachable/foreign hub (%s); reclaiming executor",
                    type(e).__name__)
      feedhub.release(executor_id)

    # 3. start the feed hub; remote mode for driver-reachable roles
    hub_mode = "remote" if job_name in BACKGROUND_ROLES else "local"
    hub = feedhub.start(authkey, meta["queues"], mode=hub_mode,
                        qmax=meta.get("qmax", 1024))
    feedhub.hold(executor_id, hub)
    if meta.get("feed_transport") == "shm":
      # high-throughput input path: serialized chunks ride a native
      # shared-memory ring instead of manager-proxy queues; control/error/
      # output queues stay on the hub
      from tensorflowonspark_tpu.control import shmring
      if shmring.available():
        ring_name = "/tos_feed_%x_%d" % (meta["id"] & 0xFFFFFFFF,
                                         executor_id)
        if restart_count:
          # generation-suffix the relaunched node's ring: co-host feeder
          # processes cache opened rings by name (shmring.open_cached), so
          # reusing the dead predecessor's name would hand them a stale
          # mapping of an unlinked segment. Reap the old generations'
          # segments while we're here.
          shmring.unlink_stale(ring_name)
          for gen in range(1, restart_count):
            shmring.unlink_stale("%s_r%d" % (ring_name, gen))
          ring_name = "%s_r%d" % (ring_name, restart_count)
        ring = shmring.ShmRing.create(ring_name,
                                      meta.get("shm_capacity",
                                               64 * 1024 * 1024))
        shmring.hold(executor_id, ring)
        hub.set("ring_name", ring_name)
      else:
        logger.warning("feed_transport='shm' requested but native ring "
                       "unavailable; falling back to queue transport")
    hostinfo.write_executor_id(executor_id, working_dir)
    with open(os.path.join(working_dir, HUB_ADDR_FILE), "w") as f:
      f.write("%s:%d" % hub.addr)

    # 5. reserve a port for the JAX coordinator / collectives endpoint
    # (parity with TF GRPC port reservation, :344-352); env pin supported
    tmp_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    tmp_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    tmp_sock.bind(("", int(os.environ.get(ENV_NODE_PORT, "0"))))
    port = tmp_sock.getsockname()[1]

    # Steps 6-8 run with the reserved port open in a PERSISTENT executor
    # process: a bring-up failure (TB spawn error, reservation timeout,
    # chip-allocation error) must release the socket or every supervised
    # retry leaks one fd into the executor (TOS006).
    try:
      # 6. TensorBoard on chief / worker:0 (parity :292-329)
      tb_info = None
      if meta.get("tensorboard") and is_chief(job_name, task_index,
                                              meta["cluster_template"]):
        log_dir = meta.get("log_dir") or os.path.join(working_dir,
                                                      "tensorboard")
        os.makedirs(paths.strip_scheme(log_dir), exist_ok=True)
        tb_info = _spawn_tensorboard(paths.strip_scheme(log_dir))
        if tb_info:
          hub.set("tb_pid", tb_info["pid"])
          hub.set("tb_url", tb_info["url"])

      # 7. register and wait for the whole cluster (parity :332-370)
      host = hostinfo.get_ip_address()
      client = rendezvous.Client(tuple(meta["server_addr"]))
      reservation = {
          "executor_id": executor_id,
          "host": host,
          "job_name": job_name,
          "task_index": task_index,
          "port": port,
          "hub_addr": list(hub.addr),
          "pid": os.getpid(),
          "tb_url": tb_info["url"] if tb_info else None,
          # a reclaimed stale hub proves this is a retry of a dead
          # predecessor, not a concurrent task — the rendezvous replaces
          # instead of flagging a duplicate (Reservations.add)
          "reclaimed": reclaimed,
          # restart generation: lets the supervisor recognize THIS
          # relaunch's registration (the pid alone is ambiguous — an
          # ENGINE-mode relaunch reuses the executor process)
          "restart": restart_count,
      }
      try:
        client.register(reservation)
        cluster_info = client.await_reservations(
            timeout=meta.get("reservation_timeout", 600))
      finally:
        # a reservation timeout is the COMMON bring-up failure; without
        # this the persistent executor leaks one connected client fd per
        # supervised retry (TOS006)
        client.close()

      # 7.5 TPU chip allocation (replaces nvidia-smi GPU allocation,
      # parity :179-239). Runs AFTER reservation so the host-local worker
      # index comes from the actual host population in cluster_info (parity
      # with the reference's cluster-spec-derived local index, :386-388) —
      # executor ids are NOT contiguous per host, so id % workers_per_host
      # would double-claim chips.
      num_chips = meta.get("chips_per_node", 0)
      if num_chips and not os.environ.get(tpu_info.ENV_TEST_MODE):
        topo = tpu_info.get_topology()
        if topo is not None:
          cohosted = sorted(n["executor_id"] for n in cluster_info
                            if n["host"] == host)
          local_index = cohosted.index(executor_id)
          workers_per_host = max(1, topo.chips_per_host // num_chips)
          tpu_info.apply_chip_env(tpu_info.chip_env_for_worker(
              num_chips, local_index, workers_per_host,
              generation=topo.generation))

      # 8. synthesize the cluster spec + JAX process coordinates (the TPU
      # analog of exporting TF_CONFIG, parity :373-384)
      cluster_spec = _build_cluster_spec(cluster_info)
      table, coordinator = _jax_process_table(cluster_info)
      process_id = next((i for i, n in enumerate(table)
                         if n["executor_id"] == executor_id), -1)
    except BaseException:
      tmp_sock.close()
      raise

    ctx_kwargs = dict(
        executor_id=executor_id, job_name=job_name, task_index=task_index,
        cluster_spec=cluster_spec, default_fs=meta.get("default_fs", "file://"),
        working_dir=working_dir, coordinator_address=coordinator,
        process_id=process_id, num_processes=len(table),
        cluster_info=cluster_info, restart_count=restart_count)
    hb_interval = meta.get("heartbeat_interval")

    # 9. release-port semantics (parity :400-405): by default the reserved
    # port is released before the user fn; with release_port=False user code
    # calls ctx.release_port() itself right before jax.distributed.initialize
    release_now = meta.get("release_port", True)

    # 10. run the user main fn per role (parity :417-463)
    if isinstance(tf_args, list):
      sys.argv = [sys.argv[0] if sys.argv else "main"] + list(tf_args)
    # cluster-level training knobs (train_unroll → TOS_TRAIN_UNROLL)
    # export here so BOTH the foreground fn and the spawned background
    # runner (which inherits this env) resolve the same defaults
    _apply_node_env(meta)

    if job_name in BACKGROUND_ROLES or meta["input_mode"] == 1:
      # background execution; foreground either returns (workers, so feeding
      # tasks can be scheduled onto this executor) or parks on the control
      # queue (ps/evaluator) until the driver sends None (parity :431-458)
      tmp_sock.close()
      import multiprocessing as mp
      proc = mp.get_context("spawn").Process(
          target=_background_runner,
          args=(fn_bytes, tf_args, ctx_kwargs, list(hub.addr), authkey,
                list(meta["server_addr"]), hb_interval),
          daemon=True, name="tos-node-%d" % executor_id)
      proc.start()
      hub.set("node_pid", proc.pid)
      if job_name in BACKGROUND_ROLES:
        control = hub.get_queue("control")
        while True:
          items = control.get_many(1, timeout=1.0)
          if items and items[0] is None:
            break
        # flip the state off "running" FIRST — sidecar fns (e.g. the eval
        # sidecar) poll it as their stop signal — then join the background
        # process (bounded) so its work is durably done before 'stopped'
        # is reported: the driver's stop used to race a fn still starting
        hub.set("state", "terminating")
        proc.join(timeout=60)
        if proc.is_alive():
          logger.warning("%s:%d background process still running at stop; "
                         "terminating", job_name, task_index)
          proc.terminate()
        hub.set("state", "stopped")
      return [executor_id]
    else:
      # foreground execution (FILES mode workers, parity :459-463); beats
      # come from THIS process — the one the user fn runs in — so a
      # kill/hang of the worker is what stops them
      if release_now:
        tmp_sock.close()
        tmp_sock = None
      sender = None
      if hb_interval:
        sender = rendezvous.HeartbeatSender(
            tuple(meta["server_addr"]), executor_id,
            interval=hb_interval).start()
      shipper = _start_obs_shipper(meta["server_addr"], executor_id, sender)
      ctx = TPUNodeContext(hub=hub, tmp_socket=tmp_sock, heartbeat=sender,
                           **ctx_kwargs)
      # foreground workers jit in THIS process: persistent compilation
      # cache (TOS_COMPILE_CACHE) goes live before the user fn compiles
      _setup_compile_cache()
      try:
        cloudpickle.loads(fn_bytes)(tf_args, ctx)
        hub.set("state", "stopped")
      except BaseException:
        tb = traceback.format_exc()
        try:
          hub.get_queue("error").put(tb)
          hub.set("state", "stopped")
        except Exception:  # noqa: BLE001
          pass
        raise
      finally:
        if shipper is not None:
          shipper.stop()
        if sender is not None:
          sender.stop()
      return [executor_id]

  return _mapfn


def driver_node_main(mapfn_bytes: bytes, executor_id: int,
                     workdir: str) -> None:
  """Entry point for a node hosted on the DRIVER machine (driver_ps_nodes,
  parity: reference TFCluster.py:298-316): runs the same bring-up mapfn a
  regular executor would, in its own working directory."""
  import cloudpickle
  os.makedirs(workdir, exist_ok=True)
  os.chdir(workdir)
  mapfn = cloudpickle.loads(mapfn_bytes)
  mapfn(iter([executor_id]))


# --- data-plane task factories (parity: TFSparkNode.train/inference) --------


def _get_hub(cluster_info: List[dict], executor_id: int, authkey: bytes):
  """Locate the feed hub of the node that owns this executor working dir
  (parity: TFSparkNode._get_manager, TFSparkNode.py:128-155).

  The working dir's ``hub_addr`` file is authoritative: a supervised
  relaunch starts a FRESH hub and rewrites the file, while ``cluster_info``
  pickled into an already-submitted feed task still names the dead one.
  Falls back to cluster_info when the file is missing/unreadable.
  """
  hub_file = os.path.join(os.getcwd(), HUB_ADDR_FILE)
  try:
    with open(hub_file) as f:
      host, port = f.read().strip().split(":")
    return feedhub.connect((host, int(port)), authkey)
  except Exception:  # noqa: BLE001 - fall back to the reservation table
    pass
  for n in cluster_info:
    if n["executor_id"] == executor_id:
      return feedhub.connect(tuple(n["hub_addr"]), authkey)
  raise RuntimeError("no cluster node found for executor %d" % executor_id)


def _open_advertised_ring(hub, qname: str):
  """The node's shm ring adapter, or None (not advertised / unreachable).

  One shared resolution for the producer and consumer paths so their
  fallback behavior cannot drift."""
  if qname != "input":
    return None
  ring_name = hub.get("ring_name")
  if not ring_name:
    return None
  from tensorflowonspark_tpu.control import shmring
  try:
    return shmring.RingQueueAdapter(shmring.open_cached(ring_name))
  except Exception as e:  # noqa: BLE001 - cross-host/absent/released ring
    logger.warning("advertised shm ring %r unreachable from this process "
                   "(%s); using the hub queue", ring_name, type(e).__name__)
    return None


def input_channel(hub, qname: str = "input"):
  """PRODUCER-side input stream: the shared-memory ring when the node
  advertises one (feed_transport='shm') and it is reachable from this
  process, else the hub queue. Both expose the same put/get/join surface
  (control.shmring.RingQueueAdapter).

  A feeder task scheduled onto a DIFFERENT host (multi-host Spark) cannot
  open the node's ring — it falls back to the hub queue, and the node's
  consumer drains both (:class:`DualInput`)."""
  ring = _open_advertised_ring(hub, qname)
  return ring if ring is not None else hub.get_queue(qname)


def _slice_chunk(chunk, a: int, b: int):
  """Row-range slice of a pending chunk (row list or ColumnChunk)."""
  from tensorflowonspark_tpu.control import chunkcodec
  if isinstance(chunk, chunkcodec.ColumnChunk):
    return chunkcodec.ColumnChunk([c[a:b] for c in chunk.cols],
                                  chunk.scalar, chunk.tuples, b - a)
  return chunk[a:b]


def put_rows_chunk(channel, rows, timeout=None, stats=None) -> int:
  """Ship one feed chunk as one or more chunk-boundary envelopes.

  The chunk is encoded ONCE in the feeder process (columnar for
  homogeneous rows, with per-column wire encodings —
  ``control/chunkcodec.py``) and travels as one unit on either transport:
  a ring payload, or a hub-queue ``ChunkEnvelope`` whose manager pickle
  is a bytes memcpy instead of a per-row object walk. Chunk boundaries
  survive to the consumer, which is what lets ``DataFeed`` assemble
  batches from column views instead of row tuples.

  Splitting operates on the ENCODED payload size (compression widens the
  effective row budget): oversized chunks halve at the row level until
  every envelope fits ``chunkcodec.MAX_PAYLOAD``, in row order. A SINGLE
  row whose encoded payload still exceeds the bound raises
  :class:`chunkcodec.OversizedRowError` — a structured error instead of
  the former unbounded recursion.

  ``rows`` may be a row list or an already-columnar ``ColumnChunk``
  (e.g. a pushdown segment's output). Returns total encoded bytes
  shipped; ``stats`` (optional dict) accumulates per-column encoding
  counts for chunks that shipped.
  """
  from tensorflowonspark_tpu.control import chunkcodec
  from tensorflowonspark_tpu.obs import metrics as obs_metrics
  if not isinstance(rows, chunkcodec.ColumnChunk):
    rows = list(rows)
  enc_counts: Dict[str, int] = {}
  total_bytes = 0
  total_rows = 0
  # LIFO work stack: push the back half first so rows ship in order
  stack = [rows]
  while stack:
    chunk = stack.pop()
    n = chunk.n if isinstance(chunk, chunkcodec.ColumnChunk) else len(chunk)
    tally: Dict[str, int] = {}
    payload = chunkcodec.encode(chunk, tally)
    if len(payload) > chunkcodec.MAX_PAYLOAD:
      if n <= 1:
        raise chunkcodec.OversizedRowError(
            "a single row encodes to %d bytes, above the transport bound "
            "(chunkcodec.MAX_PAYLOAD = %d); it cannot be split further at "
            "the row level" % (len(payload), chunkcodec.MAX_PAYLOAD))
      half = n // 2
      stack.append(_slice_chunk(chunk, half, n))
      stack.append(_slice_chunk(chunk, 0, half))
      continue
    channel.put_chunk(n, payload, block=True, timeout=timeout)
    total_bytes += len(payload)
    total_rows += n
    # merge the tally only for envelopes that actually shipped (an
    # oversized encode attempt is re-encoded after the split)
    for name, cnt in tally.items():
      enc_counts[name] = enc_counts.get(name, 0) + cnt
  if stats is not None:
    for name, cnt in enc_counts.items():
      stats[name] = stats.get(name, 0) + cnt
  reg = obs_metrics.active()
  if reg is not None and total_rows:
    reg.counter("feed.wire_bytes").inc(total_bytes)
    reg.counter("feed.wire_rows").inc(total_rows)
    for name, cnt in enc_counts.items():
      reg.counter("feed.wire_enc." + name).inc(cnt)
  return total_bytes


class _ChunkSizer(object):
  """Adaptive rows-per-envelope targeting ``target`` encoded bytes.

  Tracks an EWMA of observed encoded bytes per SOURCE row (pushdown and
  compression both fold into the ratio: a selective filter or a 4x codec
  simply makes source rows cheap on the wire, so the next envelope
  carries more of them). The row target stays clamped to
  ``[_ADAPT_MIN_ROWS, _ADAPT_MAX_ROWS]`` both ways."""

  __slots__ = ("target", "rows", "_bpr")

  def __init__(self, base_rows: int, target_bytes: int):
    self.target = int(target_bytes)
    self.rows = max(_ADAPT_MIN_ROWS, min(int(base_rows), _ADAPT_MAX_ROWS))
    self._bpr = 0.0

  def observe(self, n_rows: int, n_bytes: int) -> None:
    if n_rows <= 0:
      return
    bpr = n_bytes / float(n_rows)
    self._bpr = bpr if not self._bpr else 0.5 * self._bpr + 0.5 * bpr
    if self._bpr > 0:
      self.rows = max(_ADAPT_MIN_ROWS,
                      min(int(self.target / self._bpr), _ADAPT_MAX_ROWS))


def _feed_plan(cluster_meta: Dict, chunk_size: Optional[int]):
  """Resolve one feeder task's shipping plan from cluster_meta (executor
  side): ``(chunk_size, run_segment, sizer)``. The pushdown segment
  compiles once per task; the sizer exists only when a byte budget is
  set (``feed_target_bytes`` cluster param, else ``TOS_FEED_TARGET_BYTES``)."""
  from tensorflowonspark_tpu.control import chunkcodec
  chunk_size = chunk_size or cluster_meta.get("feed_chunk_size", 256)
  # a new stream's columns owe nothing to the last one: drop any probe
  # backoff left by a previous feeder task in this process, or a fresh
  # compressible stream would ship its leading chunks raw
  chunkcodec._probe_backoff.clear()
  segment = cluster_meta.get("feed_segment")
  run_segment = segment.compile() if segment is not None else None
  target = cluster_meta.get("feed_target_bytes")
  if not target:
    try:
      target = int(os.environ.get(ENV_FEED_TARGET_BYTES, "0") or 0)
    except ValueError:
      target = 0
  sizer = _ChunkSizer(chunk_size, target) if target and target > 0 else None
  return chunk_size, run_segment, sizer


def _flush_chunk(queue, chunk, run_segment, sizer, timeout,
                 stats=None) -> int:
  """Apply the pushdown segment (if any) to one accumulated source chunk
  and ship the survivors. Returns rows actually DELIVERED (post-segment)
  — a pushed-down filter drops rows feeder-side, and inference collects
  one result per delivered row, not per source row. The sizer observes
  SOURCE rows against shipped bytes so its budget covers the whole
  segment+codec pipeline."""
  src_n = len(chunk)
  out = chunk
  if run_segment is not None:
    out = run_segment(chunk)
  n = 0 if out is None else (out.n if hasattr(out, "n") else len(out))
  nbytes = put_rows_chunk(queue, out, timeout=timeout, stats=stats) \
      if n else 0
  if sizer is not None and src_n:
    sizer.observe(src_n, nbytes)
  return n


class DualInput(object):
  """CONSUMER-side input draining the shm ring AND the hub queue.

  Co-host feeders (and the end-of-feed markers from co-hosted shutdown
  tasks) arrive on the ring; feeders on other hosts — and shutdown tasks
  the shared queue placed off-host — fall back to the hub queue.
  Per-partition row order is
  preserved because any single feeder uses exactly one channel.
  ``task_done`` routes to whichever channel produced the last batch, so
  queue join backpressure still works for remote feeders.

  An end-of-feed ``None`` arriving on the ring (shutdown marker, or the
  adapter's synthesized marker when the ring closes) is HELD BACK while
  the hub queue still has rows — a marker must never overtake remote
  feeders' in-flight data.
  """

  def __init__(self, ring, queue):
    self._ring = ring
    self._queue = queue
    self._last = None
    self._stash = None    # ring tail (from the marker on) awaiting drain
    self._stash_chunk = None  # held-back end-of-feed chunk (get_chunk path)

  def _from(self, ch, got):
    self._last = ch
    return got

  def _deliver_ring(self, got, max_items: int):
    # identity scan, not `None in got`: rows may be numpy arrays, whose
    # __eq__ is elementwise and makes `in`/.index raise on truth-testing
    idx = next((i for i, r in enumerate(got) if r is None), -1)
    if idx >= 0 and not self._queue.empty():
      self._stash = got[idx:]
      prefix = got[:idx]
      if prefix:
        return self._from(self._ring, prefix)
      queued = self._queue.get_many(max_items, block=False)
      if queued:
        return self._from(self._queue, queued)
      # the queue drained between the check and the read: release now
      out, self._stash = self._stash, None
      return self._from(self._ring, out)
    return self._from(self._ring, got)

  def get_many(self, max_items: int, block: bool = True, timeout=None):
    import time as _time
    if self._stash is not None:
      queued = self._queue.get_many(max_items, block=False)
      if queued:
        return self._from(self._queue, queued)
      out, self._stash = self._stash, None
      return self._from(self._ring, out)
    # same blocking contract as the single-channel queues: timeout=None
    # blocks until data arrives (alternating short polls of both channels)
    deadline = None if timeout is None else _time.monotonic() + timeout
    while True:
      got = self._ring.get_many(max_items, block=False)
      if got:
        return self._deliver_ring(got, max_items)
      got = self._queue.get_many(max_items, block=False)
      if got:
        return self._from(self._queue, got)
      if not block:
        return []
      remaining = None if deadline is None else deadline - _time.monotonic()
      if remaining is not None and remaining <= 0:
        return []
      wait = 0.25 if remaining is None else min(remaining, 0.25)
      got = self._ring.get_many(max_items, block=True, timeout=wait)
      if got:
        return self._deliver_ring(got, max_items)

  def _ring_chunk(self, got, max_rows: int):
    """Deliver a ring chunk, holding back an end-of-feed marker while the
    hub queue still has remote feeders' data (get_chunk analog of
    ``_deliver_ring``)."""
    if got[0] == "marker" and got[1] is None and not self._queue.empty():
      queued = self._queue.get_chunk(max_rows, block=False)
      if queued:
        self._stash_chunk = got
        return self._from(self._queue, queued)
      # the queue drained between the check and the read: release now
    return self._from(self._ring, got)

  def get_chunk(self, max_rows: int = 1024, block: bool = True,
                timeout=None):
    """Chunk-granular dequeue over both channels (``None`` on timeout).

    Same contract as the single-channel ``get_chunk``: one chunk-boundary
    unit per call; an end-of-feed ``None`` chunk from the ring waits for
    the hub queue to drain, exactly like the row-granular path."""
    import time as _time
    if self._stash_chunk is not None:
      queued = self._queue.get_chunk(max_rows, block=False)
      if queued:
        return self._from(self._queue, queued)
      out, self._stash_chunk = self._stash_chunk, None
      return self._from(self._ring, out)
    deadline = None if timeout is None else _time.monotonic() + timeout
    while True:
      got = self._ring.get_chunk(max_rows, block=False)
      if got:
        return self._ring_chunk(got, max_rows)
      got = self._queue.get_chunk(max_rows, block=False)
      if got:
        return self._from(self._queue, got)
      if not block:
        return None
      remaining = None if deadline is None else deadline - _time.monotonic()
      if remaining is not None and remaining <= 0:
        return None
      wait = 0.25 if remaining is None else min(remaining, 0.25)
      got = self._ring.get_chunk(max_rows, block=True, timeout=wait)
      if got:
        return self._ring_chunk(got, max_rows)

  def task_done(self, n: int = 1) -> None:
    if self._last is not None:
      self._last.task_done(n)

  def qsize(self) -> int:
    return self._ring.qsize() + self._queue.qsize()

  def empty(self) -> bool:
    return self.qsize() == 0


def consumer_channel(hub, qname: str = "input"):
  """The node-side input stream: ring+queue dual when a ring is
  advertised and reachable (see :class:`DualInput`), else the hub queue."""
  ring = _open_advertised_ring(hub, qname)
  if ring is not None:
    return DualInput(ring, hub.get_queue(qname))
  return hub.get_queue(qname)


def _check_errors(hub, where: str) -> None:
  """Poll the error queue; re-raise worker tracebacks on the feeder/driver
  side (parity: TFSparkNode.py:508-515)."""
  eq = hub.get_queue("error")
  errs = eq.get_many(16, block=False)
  if errs:
    # put back so shutdown's check still sees it (parity :644-650)
    eq.put_many(errs)
    raise RuntimeError("worker error detected during %s:\n%s"
                       % (where, "\n".join(str(e) for e in errs)))


_NO_ITEM = object()


def _materialize_partition(iterator):
  """Resolve a lazy partition handle on the executor.

  A partition consisting of exactly ONE zero-arg callable (e.g. from
  ``data.dfutil.load_tfrecords(lazy=True)``) is a handle: call it HERE so
  rows are produced executor-side and never ship through the driver (the
  feed-plane counterpart of save_as_tfrecords' callable partitions;
  parity: reference loadTFRecords parsing records on executors,
  dfutil.py:44-81). Anything else passes through untouched.
  """
  import itertools
  first = next(iterator, _NO_ITEM)
  if first is _NO_ITEM:
    return iter(())
  if callable(first):
    second = next(iterator, _NO_ITEM)
    if second is _NO_ITEM:
      return iter(first())
    return itertools.chain([first, second], iterator)
  return itertools.chain([first], iterator)


def make_train_fn(cluster_info, cluster_meta, feed_timeout=600, qname="input",
                  chunk_size=None):
  """Feeder task: push one data partition into the local node's input queue.

  TPU-first redesign of the reference's row-at-a-time loop
  (TFSparkNode.py:500-502): rows move as chunk-boundary envelopes via
  ``put_rows_chunk`` — encoded once (columnar for homogeneous rows) and
  shipped whole — preserving blocking backpressure and the
  terminating-state drain semantics (TFSparkNode.py:492-531).
  ``chunk_size`` defaults to the cluster's ``feed_chunk_size``; a
  ``feed_segment`` in cluster_meta (datapipe pushdown) runs here before
  the codec, and a ``feed_target_bytes`` budget sizes chunks adaptively.
  """
  authkey = cluster_meta["authkey"]

  def _train(iterator):
    executor_id = hostinfo.read_executor_id(os.getcwd())
    from tensorflowonspark_tpu.utils import chaos
    chaos.stall_point("feeder", index=executor_id)
    hub = _get_hub(cluster_info, executor_id, authkey)
    state = hub.get("state")
    queue = input_channel(hub, qname)
    if state == "terminating":
      # user called DataFeed.terminate(): consume and discard the partition
      # so the engine job completes (parity :492-496). The RAW iterator is
      # drained — a lazy handle is discarded uncalled, never decoded
      logger.info("node terminating; skipping partition feed")
      for _ in iterator:
        pass
      return [0]
    shipper = _ensure_feeder_shipper(cluster_meta.get("server_addr"),
                                     executor_id)
    size, run_segment, sizer = _feed_plan(cluster_meta, chunk_size)
    iterator = _materialize_partition(iterator)
    rows = 0
    flushes = 0
    chunk = []
    for item in iterator:
      chunk.append(item)
      if len(chunk) >= (sizer.rows if sizer is not None else size):
        rows += len(chunk)
        _flush_chunk(queue, chunk, run_segment, sizer, feed_timeout)
        chunk = []
        flushes += 1
        # poll the error queue every 8th flushed chunk — at the flush
        # point only (a per-item check would re-fire hundreds of times
        # while the count sits on a boundary value)
        if flushes % 8 == 0:
          _check_errors(hub, "feeding")
    if chunk:
      rows += len(chunk)
      _flush_chunk(queue, chunk, run_segment, sizer, feed_timeout)
    # wait until the consumer processed everything, surfacing errors
    # (parity :504-517)
    deadline = time.monotonic() + feed_timeout
    while not queue.join(timeout=1.0):
      _check_errors(hub, "feeding")
      if time.monotonic() > deadline:
        raise TimeoutError(
            "feed timeout (%ds) waiting for node to consume %d rows"
            % (feed_timeout, rows))
    _check_errors(hub, "feeding")
    if shipper is not None:
      # final flush: this may be the run's last feed task, and engine
      # teardown won't wait for the cadence thread's next round
      shipper.ship(timeout=5.0)
    logger.info("fed %d rows to executor %d", rows, executor_id)
    return [rows]

  return _train


def make_inference_fn(cluster_info, cluster_meta, feed_timeout=600,
                      qname="input", chunk_size=None):
  """Inference task: feed one partition, collect its results from the output
  queue (parity: TFSparkNode.inference, TFSparkNode.py:538-599)."""
  authkey = cluster_meta["authkey"]

  def _inference(iterator):
    from tensorflowonspark_tpu.control.marker import EndPartition
    iterator = _materialize_partition(iterator)
    executor_id = hostinfo.read_executor_id(os.getcwd())
    hub = _get_hub(cluster_info, executor_id, authkey)
    queue = input_channel(hub, qname)
    shipper = _ensure_feeder_shipper(cluster_meta.get("server_addr"),
                                     executor_id)
    size, run_segment, sizer = _feed_plan(cluster_meta, chunk_size)
    # `count` is rows DELIVERED to the node (post-pushdown): a pushed-down
    # filter drops rows feeder-side and they produce no results, so the
    # collection loop below must not wait for them
    count = 0
    chunk = []
    for item in iterator:
      chunk.append(item)
      if len(chunk) >= (sizer.rows if sizer is not None else size):
        count += _flush_chunk(queue, chunk, run_segment, sizer, feed_timeout)
        chunk = []
    if chunk:
      count += _flush_chunk(queue, chunk, run_segment, sizer, feed_timeout)
    if count == 0:
      return []  # empty/fully-filtered partitions short-circuit (parity :569-570)
    queue.put(EndPartition(), block=True, timeout=feed_timeout)

    deadline = time.monotonic() + feed_timeout
    while not queue.join(timeout=1.0):
      _check_errors(hub, "inference feeding")
      if time.monotonic() > deadline:
        raise TimeoutError("feed timeout (%ds) during inference" % feed_timeout)

    # collect exactly `count` results (parity :588-595)
    out_q = hub.get_queue("output")
    results = []
    while len(results) < count:
      got = out_q.get_many(count - len(results), timeout=feed_timeout)
      if not got:
        _check_errors(hub, "inference collection")
        if time.monotonic() > deadline:
          raise TimeoutError("timed out collecting inference results")
        continue
      results.extend(got)
      out_q.task_done(len(got))
    if shipper is not None:
      shipper.ship(timeout=5.0)   # final flush before the task returns
    return results

  return _inference


def _kill_tensorboard(hub) -> None:
  """SIGTERM this node's TensorBoard if it started one (parity :619-625)."""
  tb_pid = hub.get("tb_pid")
  if tb_pid:
    try:
      os.kill(int(tb_pid), 15)
    except OSError:
      pass


def make_tb_kill_fn(cluster_info, cluster_meta):
  """Engine task killing a node's TensorBoard (FILES-mode shutdown — there
  is no feed-shutdown job to fold it into, unlike ENGINE mode).

  Best-effort by design: a dead node/hub must not abort the rest of
  shutdown (server stop, sidecar stops, error propagation)."""
  authkey = cluster_meta["authkey"]

  def _kill(iterator):
    for _ in iterator:
      pass
    try:
      executor_id = hostinfo.read_executor_id(os.getcwd())
      _kill_tensorboard(_get_hub(cluster_info, executor_id, authkey))
    except Exception as e:  # noqa: BLE001 - reap is best-effort
      logger.warning("tensorboard reap skipped on this executor: %s", e)

  return _kill


def make_shutdown_fn(cluster_info, cluster_meta, grace_secs=0,
                     queues=("input",)):
  """Shutdown task: send end-of-feed, await node exit, surface late errors
  (parity: TFSparkNode.shutdown, TFSparkNode.py:602-656).

  The partition payload names the executor whose node this task stops.
  Engine shutdown tasks ride the SHARED queue, so both tasks can land on
  whichever executor frees up first — if this task acted on the slot it
  happens to occupy, one node could receive two end-of-feed markers while
  the other receives none and hangs until engine teardown. Host-local side
  effects (TensorBoard SIGTERM, /dev/shm reap) only run when the target
  node is co-hosted with this task."""
  authkey = cluster_meta["authkey"]

  def _host_of(eid):
    for n in cluster_info:
      if n["executor_id"] == eid:
        return n["hub_addr"][0]
    return None

  def _shutdown(iterator):
    target = None
    for item in iterator:
      target = item
    here = hostinfo.read_executor_id(os.getcwd())
    executor_id = here if target is None else int(target)
    if executor_id == here:
      # local: the cwd hub_addr file is authoritative (relaunched nodes
      # rewrite it; cluster_info may still name the dead hub)
      hub = _get_hub(cluster_info, executor_id, authkey)
    else:
      entry = next((n for n in cluster_info
                    if n["executor_id"] == executor_id), None)
      if entry is None:
        raise RuntimeError("no cluster node found for executor %d"
                           % executor_id)
      hub = feedhub.connect(tuple(entry["hub_addr"]), authkey)
    co_hosted = executor_id == here or _host_of(executor_id) == _host_of(here)

    if co_hosted:
      _kill_tensorboard(hub)  # pid signal — only valid on the node's host

    for qname in queues:
      input_channel(hub, qname).put(None, block=True, timeout=60)

    # wait for the node process to finish (state -> stopped)
    deadline = time.monotonic() + max(grace_secs, 0) + 600
    while hub.get("state") not in ("stopped",):
      if time.monotonic() > deadline:
        raise TimeoutError("node on executor %d did not stop" % executor_id)
      time.sleep(0.5)
    if grace_secs:
      time.sleep(grace_secs)

    # the input ring (if any) has served its purpose; unlink the shm
    # segment so repeated runs don't accumulate /dev/shm usage
    ring_name = hub.get("ring_name")
    if ring_name:
      from tensorflowonspark_tpu.control import shmring
      if executor_id == here:
        shmring.release(executor_id)
      elif co_hosted:
        # the ring is held by the target's executor process, not this one;
        # reap the segment by name (open mappings stay valid)
        shmring.unlink_stale(ring_name)

    # late-error propagation with peek-and-put-back (parity :644-650)
    eq = hub.get_queue("error")
    errs = eq.get_many(16, block=False)
    if errs:
      eq.put_many(errs)
      raise RuntimeError("worker error:\n%s" % "\n".join(str(e) for e in errs))
    # the background runner's fallback channel: a traceback it could not
    # enqueue (error queue unreachable at crash time) lands in the kv store
    last_error = hub.get("last_error")
    if last_error:
      raise RuntimeError("worker error (recovered from the hub kv store — "
                         "the error queue was unreachable when the node "
                         "crashed):\n%s" % last_error)
    return [executor_id]

  return _shutdown
