"""Fixed-memory mergeable streaming quantile sketch.

The obs plane's histograms answer "how many observations fell in this
fixed bucket" — good for rates, useless for principled tail latency:
the p99 of a fixed-bucket histogram is whatever bucket edge it straddles,
and two processes' histograms only merge if someone chose the bucket
bounds right for a latency distribution nobody has seen yet. This module
is the latency object the SLO plane (``obs.slo``) and the serving-plane
benches share instead: a KLL-style compactor-stack sketch —

- **fixed memory**: ~``k·log(n/k)`` stored values regardless of stream
  length (a few KiB at the default k);
- **mergeable**: ``merge`` of two sketches is a sketch of the
  concatenated streams with the error bounds ADDING, not compounding —
  which is what makes cluster-true percentiles possible: every executor
  ships its sketch over the OBS verb and the driver merges, instead of
  each process reporting its own local p99 (the mean of per-process
  p99s is not a p99 of anything);
- **bounded, self-reported error**: rank queries are exact until the
  first compaction (streams shorter than ``k`` are stored outright) and
  off by at most :attr:`rank_error` observations after — the sketch
  TRACKS the bound as it compacts, so a consumer can assert against it
  (``serve_bench --smoke`` does exactly that against the sorted list).

Compaction is DETERMINISTIC (per-level alternating parity instead of
KLL's coin flip): the same stream always yields the same sketch, so
parity-style tests and the delta-shipping plane never see nondeterminism.
The classic randomized analysis gives expected error ~1/k; the
deterministic variant keeps the worst-case bound this module reports
(each compaction of a weight-``w`` level displaces any rank by at most
``w``) at the cost of adversarial-stream tightness we don't need —
latencies are not adversarial.

Registered as a first-class metric kind (``MetricsRegistry.quantiles``,
type ``"sketch"``) in ``obs.metrics``: snapshots are plain msgpack/json
dicts, ``snapshot_delta`` ships the full (fixed-memory) sketch whenever
its count moved, ``apply_delta`` keeps last-write per executor, and the
read plane merges across executors (:func:`merge_snapshots`).
"""

from typing import List, Optional, Sequence

#: default compactor width: rank error after one compaction pass is
#: <= n/k-ish; at 256 the sketch holds every observation outright until
#: 256 samples (exact), and a day of per-request latencies stays ~KiB
DEFAULT_K = 256

#: hard ceiling on retained values independent of k (paranoia bound:
#: levels * k stays small anyway, but the invariant should not depend on
#: the analysis being right)
_MAX_LEVELS = 64


class QuantileSketch(object):
  """KLL-style mergeable quantile sketch with deterministic compaction.

  ``levels[i]`` holds UNSORTED values of weight ``2**i``; level 0 is the
  raw stream. When a level overflows its capacity (``k`` for the top
  levels, shrinking geometrically for lower ones), it is sorted and
  every other element is promoted to the next level — the classic KLL
  compactor, with the surviving parity alternating per level instead of
  random, so identical streams produce identical sketches.

  Thread-safety: same contract as the other metric hot paths
  (``obs.metrics``) — plain list appends under the GIL; a rare racing
  ``add`` can lose one observation, never corrupt the structure. Reads
  (``quantile``/``rank``/``snapshot``) are driver/report-side.
  """

  __slots__ = ("k", "levels", "count", "vmin", "vmax", "_compactions",
               "_parity")

  def __init__(self, k: int = DEFAULT_K):
    if k < 8:
      raise ValueError("sketch k must be >= 8, got %d" % k)
    self.k = int(k)
    self.levels: List[List[float]] = [[]]
    self.count = 0
    self.vmin: Optional[float] = None
    self.vmax: Optional[float] = None
    # per-level compaction counters: the error bound is computed from
    # these, so the sketch can report how wrong it may be
    self._compactions: List[int] = [0]
    self._parity: List[int] = [0]

  # -- write path ------------------------------------------------------------

  def add(self, value) -> None:
    v = float(value)
    self.count += 1
    if self.vmin is None or v < self.vmin:
      self.vmin = v
    if self.vmax is None or v > self.vmax:
      self.vmax = v
    self.levels[0].append(v)
    if len(self.levels[0]) >= self._capacity(0):
      self._compress()

  def extend(self, values) -> None:
    for v in values:
      self.add(v)

  def _capacity(self, level: int) -> int:
    # lower levels may shrink geometrically (they carry less weight);
    # keep it simple and safe: full k everywhere — memory is still
    # O(k log(n/k)) and the bound only tightens
    return self.k

  def _compress(self) -> None:
    for i in range(len(self.levels)):
      buf = self.levels[i]
      if len(buf) < self._capacity(i):
        continue
      if i + 1 == len(self.levels):
        if len(self.levels) >= _MAX_LEVELS:
          # unreachable in practice (2**64 observations); drop to half
          # rather than grow without bound
          buf.sort()
          del buf[::2]
          self._compactions[i] += 1
          continue
        self.levels.append([])
        self._compactions.append(0)
        self._parity.append(0)
      buf.sort()
      # alternating parity: deterministic, and successive compactions
      # cancel rather than accumulate one-sided rank drift
      start = self._parity[i] & 1
      self._parity[i] ^= 1
      promoted = buf[start::2]
      self.levels[i + 1].extend(promoted)
      self._compactions[i] += 1
      del buf[:]

  # -- read path -------------------------------------------------------------

  @property
  def rank_error(self) -> int:
    """Worst-case rank displacement (in observations) any quantile
    answer can carry: each compaction of a weight-``2**i`` level moves
    any rank by at most ``2**i``. Zero until the first compaction —
    short streams are EXACT."""
    return sum(c * (1 << i) for i, c in enumerate(self._compactions))

  @property
  def relative_error(self) -> float:
    """``rank_error`` as a fraction of the stream (0.0 when empty)."""
    if not self.count:
      return 0.0
    return self.rank_error / float(self.count)

  def _weighted(self) -> List[tuple]:
    out = []
    for i, buf in enumerate(self.levels):
      w = 1 << i
      out.extend((v, w) for v in buf)
    out.sort(key=lambda vw: vw[0])
    return out

  def quantile(self, q: float) -> Optional[float]:
    """The value at quantile ``q`` in [0, 1] (None when empty): the
    smallest retained value whose cumulative weight reaches ``q·count``
    — nearest-rank semantics, exact until the first compaction."""
    if not 0.0 <= q <= 1.0:
      raise ValueError("quantile must be in [0, 1], got %r" % (q,))
    items = self._weighted()
    if not items:
      return None
    target = q * self.count
    cum = 0
    for v, w in items:
      cum += w
      if cum >= target:
        return v
    return items[-1][0]

  def rank(self, value) -> int:
    """Approximate count of observations <= ``value`` (the CDF numerator
    — ``count - rank(threshold)`` is the over-threshold count the SLO
    plane's bad-fraction rides on)."""
    v = float(value)
    total = 0
    for i, buf in enumerate(self.levels):
      w = 1 << i
      for x in buf:
        if x <= v:
          total += w
    return min(total, self.count)

  # -- merge + serialization -------------------------------------------------

  def merge(self, other: "QuantileSketch") -> "QuantileSketch":
    """Fold ``other`` into self (returns self). Error bounds ADD: the
    merged ``rank_error`` is at most the sum of both plus whatever new
    compactions the fold itself triggers."""
    if other.count == 0:
      return self
    while len(self.levels) < len(other.levels):
      self.levels.append([])
      self._compactions.append(0)
      self._parity.append(0)
    for i, buf in enumerate(other.levels):
      self.levels[i].extend(buf)
      self._compactions[i] += other._compactions[i] \
          if i < len(other._compactions) else 0
    self.count += other.count
    if other.vmin is not None and (self.vmin is None
                                   or other.vmin < self.vmin):
      self.vmin = other.vmin
    if other.vmax is not None and (self.vmax is None
                                   or other.vmax > self.vmax):
      self.vmax = other.vmax
    self._compress()
    return self

  def to_dict(self) -> dict:
    """msgpack/json-safe snapshot (the ``"sketch"`` metric payload)."""
    return {"k": self.k, "count": self.count, "min": self.vmin,
            "max": self.vmax, "levels": [list(b) for b in self.levels],
            "compactions": list(self._compactions)}

  @classmethod
  def from_dict(cls, d: dict) -> "QuantileSketch":
    sk = cls(int(d.get("k") or DEFAULT_K))
    levels = d.get("levels") or [[]]
    sk.levels = [[float(v) for v in b] for b in levels]
    sk.count = int(d.get("count") or 0)
    sk.vmin = d.get("min")
    sk.vmax = d.get("max")
    comps = d.get("compactions") or []
    sk._compactions = [int(c) for c in comps] or [0] * len(sk.levels)
    while len(sk._compactions) < len(sk.levels):
      sk._compactions.append(0)
    sk._parity = [0] * len(sk.levels)
    return sk


def merge_snapshots(snaps: Sequence[Optional[dict]],
                    k: int = DEFAULT_K) -> QuantileSketch:
  """Merge sketch snapshot dicts (per-executor ``"sketch"`` payloads,
  Nones skipped) into one cluster-true sketch — the read-plane half of
  delta shipping: executors ship full fixed-memory sketches, the driver
  keeps last-write per executor, and queries merge across them."""
  out = QuantileSketch(k)
  for s in snaps:
    if not s:
      continue
    data = s.get("data") if "data" in s else s
    if not data or not data.get("count"):
      continue
    out.merge(QuantileSketch.from_dict(data))
  return out
