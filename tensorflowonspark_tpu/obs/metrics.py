"""Process-local metrics registry: counters, gauges, fixed-bucket histograms.

The measurement plane's L0: every instrumented seam (DataFeed stages, the
ServingEngine loop, StepTimer, the ClusterSupervisor) records into ONE
per-process :class:`MetricsRegistry`. Design constraints, in order:

- **lock-cheap hot path**: recording must be safe to leave enabled inside
  the feed/serve/train loops. Metric objects are plain attribute updates
  guarded only by the GIL — no per-observation lock, no allocation. Under
  concurrent writers a counter may (rarely) lose an increment to a
  read-modify-write race; that is the documented trade for a hot path
  that costs tens of nanoseconds. Anything that must be exact (the
  parity/accounting state of the runtime itself) does NOT live here.
- **registration is the cold path**: ``counter()/gauge()/histogram()``
  take a lock and get-or-create; call them once at setup and keep the
  returned handle.
- **delta shipping**: snapshots are plain msgpack-able dicts;
  :func:`snapshot_delta` / :func:`apply_delta` turn them into the bounded
  increments the rendezvous ``OBS`` verb ships driver-ward (counters and
  histograms subtract; gauges report last-written value).

Enablement rides ``TOS_OBS`` (registered: :data:`ENV_OBS`): when set (and
not ``"0"``), :func:`active` lazily builds the process registry; when
unset it returns None and every instrumented seam stays on its zero-cost
``if reg is None`` guard. Tests (or embedding apps) can install a
registry explicitly with :func:`activate` regardless of the env.
"""

import bisect
import os
import threading
from typing import Dict, List, Optional, Sequence

from tensorflowonspark_tpu.obs import quantiles as quantiles_mod

#: master switch for the observability plane (env registry: TOS008).
#: ``TOS_OBS=1`` activates the per-process registry/tracer and the
#: executor-side delta shipper; unset/``0`` keeps every hot-path hook on
#: its None guard.
ENV_OBS = "TOS_OBS"

#: default histogram bucket upper bounds (milliseconds-flavored: the
#: instrumented seams record durations in ms)
DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0)


def enabled() -> bool:
  """True when the observability plane is switched on (``TOS_OBS``)."""
  return os.environ.get(ENV_OBS, "") not in ("", "0")


class Counter(object):
  """Monotonic count. ``inc`` is the hot path: one GIL-guarded add."""

  __slots__ = ("name", "value")

  def __init__(self, name: str):
    self.name = name
    self.value = 0.0

  def inc(self, n=1) -> None:
    self.value += n

  def snapshot(self) -> dict:
    return {"type": "counter", "value": self.value}


class Gauge(object):
  """Last-written value (occupancy, queue depth, cumulative stage secs)."""

  __slots__ = ("name", "value")

  def __init__(self, name: str):
    self.name = name
    self.value = 0.0

  def set(self, v) -> None:
    self.value = float(v)

  def snapshot(self) -> dict:
    return {"type": "gauge", "value": self.value}


class Histogram(object):
  """Fixed-bucket histogram: cumulative-style bounds, per-bucket counts.

  ``observe`` is one bisect + three GIL-guarded updates; bounds are fixed
  at creation so deltas are an elementwise subtract and merges never have
  to re-bucket.
  """

  __slots__ = ("name", "bounds", "counts", "sum", "count")

  def __init__(self, name: str, bounds: Optional[Sequence[float]] = None):
    self.name = name
    self.bounds = tuple(sorted(bounds)) if bounds else DEFAULT_BUCKETS
    # one overflow bucket past the last bound
    self.counts = [0] * (len(self.bounds) + 1)
    self.sum = 0.0
    self.count = 0

  def observe(self, v) -> None:
    v = float(v)
    self.counts[bisect.bisect_left(self.bounds, v)] += 1
    self.sum += v
    self.count += 1

  def snapshot(self) -> dict:
    return {"type": "histogram", "bounds": list(self.bounds),
            "counts": list(self.counts), "sum": self.sum,
            "count": self.count}


class Quantiles(object):
  """Mergeable streaming-quantile metric (``obs.quantiles``): the
  first-class latency object — TTFT / per-output-token time / e2e /
  queue wait record here, and the driver merges per-executor sketches
  into cluster-true percentiles (fixed-bucket histograms can't: their
  p99 is whichever bucket edge it straddles).

  ``observe`` is the hot path: one sketch ``add`` (list append +
  occasional compaction), GIL-only like every other metric here.
  """

  __slots__ = ("name", "sketch")

  def __init__(self, name: str, k: Optional[int] = None):
    self.name = name
    self.sketch = quantiles_mod.QuantileSketch(
        k if k else quantiles_mod.DEFAULT_K)

  def observe(self, v) -> None:
    self.sketch.add(v)

  @property
  def count(self) -> int:
    return self.sketch.count

  def quantile(self, q: float):
    return self.sketch.quantile(q)

  def snapshot(self) -> dict:
    # the full sketch IS the snapshot: fixed memory, so shipping it
    # whole (see snapshot_delta) keeps the wire bounded and makes
    # retries idempotent (last-write at the sink, merge at read time)
    return {"type": "sketch", "count": self.sketch.count,
            "data": self.sketch.to_dict()}


class MetricsRegistry(object):
  """Get-or-create metric store; handles are the hot-path objects."""

  def __init__(self):
    self._lock = threading.Lock()
    self._metrics: Dict[str, object] = {}

  def _get(self, name: str, cls, *args):
    with self._lock:
      m = self._metrics.get(name)
      if m is None:
        m = cls(name, *args)
        self._metrics[name] = m
      elif not isinstance(m, cls):
        raise TypeError("metric %r already registered as %s"
                        % (name, type(m).__name__))
      return m

  def counter(self, name: str) -> Counter:
    return self._get(name, Counter)

  def gauge(self, name: str) -> Gauge:
    return self._get(name, Gauge)

  def histogram(self, name: str,
                bounds: Optional[Sequence[float]] = None) -> Histogram:
    return self._get(name, Histogram, bounds)

  def quantiles(self, name: str, k: Optional[int] = None) -> Quantiles:
    return self._get(name, Quantiles, k)

  def names(self) -> List[str]:
    with self._lock:
      return sorted(self._metrics)

  def snapshot(self) -> Dict[str, dict]:
    """{name: metric snapshot} — plain builtins, msgpack/json-safe."""
    with self._lock:
      metrics = list(self._metrics.items())
    return {name: m.snapshot() for name, m in metrics}


# -- delta arithmetic (the OBS-verb shipping format) --------------------------


def snapshot_delta(cur: Dict[str, dict],
                   prev: Dict[str, dict]) -> Dict[str, dict]:
  """What changed between two :meth:`MetricsRegistry.snapshot` calls.

  Counters/histograms subtract (a metric absent from ``prev`` ships its
  full value); gauges ship their current value when it changed. Quantile
  sketches (``"sketch"``) ship their FULL fixed-memory state whenever the
  observation count moved: a sketch cannot subtract, but it is bounded
  (~KiB) and last-write idempotent, so re-shipping after a failed ack is
  harmless and the read plane merges per-executor last-writes
  (``obs.quantiles.merge_snapshots``) into cluster-true percentiles.
  Metrics with no change are omitted — including settled gauges — so an
  idle process ships empty deltas and the shipper's keep-the-wire-quiet
  short-circuit can actually fire.
  """
  out: Dict[str, dict] = {}
  for name, snap in cur.items():
    old = prev.get(name)
    kind = snap["type"]
    if old is None or old.get("type") != kind:
      if kind in ("histogram", "sketch") and snap["count"] == 0:
        continue
      if kind not in ("histogram", "sketch") and snap["value"] == 0:
        continue
      out[name] = snap
      continue
    if kind == "sketch":
      if snap["count"] == old["count"]:
        continue
      out[name] = snap
    elif kind == "histogram":
      if snap["count"] == old["count"]:
        continue
      out[name] = {"type": kind, "bounds": snap["bounds"],
                   "counts": [a - b for a, b in zip(snap["counts"],
                                                    old["counts"])],
                   "sum": snap["sum"] - old["sum"],
                   "count": snap["count"] - old["count"]}
    elif kind == "counter":
      if snap["value"] == old["value"]:
        continue
      out[name] = {"type": kind, "value": snap["value"] - old["value"]}
    else:  # gauge: last-written value (not a delta), only when it moved
      if snap["value"] == old["value"]:
        continue
      out[name] = snap
  return out


def apply_delta(total: Dict[str, dict], delta: Dict[str, dict]) -> None:
  """Merge one shipped delta into a cumulative snapshot-shaped dict
  (the driver-side accumulation the ObsSink keeps per executor)."""
  for name, d in delta.items():
    cur = total.get(name)
    kind = d.get("type")
    if cur is None or cur.get("type") != kind:
      total[name] = {k: (list(v) if isinstance(v, list) else v)
                     for k, v in d.items()}
      continue
    if kind == "sketch":
      # last-write: the shipped sketch is the executor's full cumulative
      # state (cross-executor aggregation merges at read time)
      total[name] = {"type": "sketch", "count": d["count"],
                     "data": d["data"]}
    elif kind == "histogram":
      if list(cur["bounds"]) != list(d["bounds"]):
        total[name] = {k: (list(v) if isinstance(v, list) else v)
                       for k, v in d.items()}
        continue
      cur["counts"] = [a + b for a, b in zip(cur["counts"], d["counts"])]
      cur["sum"] += d["sum"]
      cur["count"] += d["count"]
    elif kind == "counter":
      cur["value"] += d["value"]
    else:
      cur["value"] = d["value"]


# -- live-stats snapshot-subtract helper --------------------------------------


class StatsSnapshot(object):
  """Point-in-time baseline over a LIVE stats dict mutated by daemon
  threads (``DataFeed.stats``, ``ServingEngine.stats``).

  Zeroing such a dict races the owning thread's read-modify-writes, and
  per-caller ``base = dict(stats)`` copies had already drifted apart
  across the benches — this is the ONE snapshot-subtract implementation.
  ``delta()`` reads the live dict again and returns current-minus-base
  for every key present at snapshot time (new keys are ignored: the
  caller asked about the keys it saw).

  NESTED dicts (``GraphExecutor.stats["stages"]`` — the datapipe
  executor's per-stage counters, each mutated by that stage's worker
  pool) snapshot and subtract recursively, so multi-stage bench
  readouts can't race live worker ``+=`` either.
  """

  def __init__(self, live: Dict[str, float]):
    self._live = live
    self._base = self._copy(live)

  @classmethod
  def _copy(cls, d: Dict) -> Dict:
    return {k: (cls._copy(v) if isinstance(v, dict) else v)
            for k, v in d.items()}

  @classmethod
  def _sub(cls, live: Dict, base: Dict) -> Dict:
    out = {}
    for k, v in base.items():
      cur = live.get(k, v)
      if isinstance(v, dict):
        out[k] = cls._sub(cur if isinstance(cur, dict) else v, v)
      else:
        out[k] = cur - v
    return out

  def delta(self) -> Dict[str, float]:
    return self._sub(self._live, self._base)


def snapshot_stats(live: Dict[str, float]) -> StatsSnapshot:
  """Take a subtraction baseline over a live stats dict."""
  return StatsSnapshot(live)


# -- the process-active registry ----------------------------------------------

_active: Optional[MetricsRegistry] = None
_active_lock = threading.Lock()


def active() -> Optional[MetricsRegistry]:
  """The process registry, or None when the obs plane is off.

  Lazily built on first call once ``TOS_OBS`` is set; instrumented seams
  cache the result and guard on None.
  """
  global _active
  if _active is None and enabled():
    with _active_lock:
      if _active is None:
        _active = MetricsRegistry()
  return _active


def activate(registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
  """Install (and return) the process registry, ignoring ``TOS_OBS``."""
  global _active
  with _active_lock:
    _active = registry if registry is not None else MetricsRegistry()
    return _active


def deactivate() -> None:
  """Drop the process registry (test isolation helper)."""
  global _active
  with _active_lock:
    _active = None
