"""Declarative SLO objectives + multi-window burn-rate evaluation.

The anomaly detectors (``obs.anomaly``) answer "is a component
misbehaving"; this module answers the operator/canary question "is the
SERVICE meeting its promises" — and does it with the two ingredients a
point threshold lacks:

- **principled latency objects**: a latency objective ("p99 TTFT ≤ X
  ms") is evaluated against the cluster-MERGED quantile sketches
  (``obs.quantiles``) the engines record per request, not against one
  process's histogram buckets. Internally every objective reduces to a
  *bad-fraction over an error budget*: "p99 ≤ X" means "at most 1% of
  requests may exceed X", so the SLI is the fraction over X — which a
  sketch answers with a rank query, and which DELTAS across a window
  (two cumulative (count, over-count) samples subtract) even though
  sketches themselves don't.
- **burn-rate alerting**: a point threshold pages on every blip and
  sleeps through slow leaks. The burn rate is ``bad_fraction /
  error_budget`` — how many times faster than sustainable the budget is
  being spent — and the alert fires only when BOTH a fast window and a
  slow window (the classic 5m/1h pair, here ``TOS_OBS_WINDOW`` and
  ``TOS_SLO_SLOW_MULT`` × it — 12× is exactly the 5m:1h ratio) exceed
  ``TOS_SLO_BURN``: the slow window proves it is sustained, the fast
  window proves it is still happening (so a recovered incident stops
  paging). A routine zero-shed rolling swap moves neither window's
  bad counts, so it stays quiet by construction — the ``fleet_degraded``
  false-positive lesson, re-applied to SLOs.

Objectives (all knobs TOS008-registered):

==========================  ==================================================
``TOS_SLO_AVAILABILITY``    availability target (default 0.999; ``0`` = off):
                            1 − bad/submitted at the CLIENT boundary — fleet
                            counters (``fleet.submitted`` vs ``fleet.rejected``
                            + ``fleet.shed``) when a fleet is present, else
                            engine counters (``serve.submitted`` vs
                            ``serve.rejected`` + ``serve.poisoned``)
``TOS_SLO_TTFT_MS``         p-quantile TTFT bound in ms (unset/0 = off) over
                            the merged ``serve.ttft_ms`` sketches
``TOS_SLO_E2E_MS``          p-quantile end-to-end latency bound in ms
                            (unset/0 = off) over ``serve.e2e_ms``
``TOS_SLO_QUANTILE``        the p in the latency objectives (default 0.99 —
                            the budget is 1 − p)
``TOS_SLO_BURN``            burn-rate threshold both windows must exceed
                            (default 14.4 — the classic page-level rate:
                            a 30-day budget gone in ~2 days)
``TOS_SLO_SLOW_MULT``       slow window as a multiple of the fast one
                            (default 12 = the 5m:1h ratio)
``TOS_SLO_MIN_EVENTS``      events the slow window must hold before a verdict
                            (default 10: one bad request out of one is a
                            sample, not an outage)
==========================  ==================================================

The :class:`SLOTracker` is driven by the :class:`~.anomaly
.AnomalyDetector` loop (sample + evaluate per pass; ``slo_burn`` rides
the detector's 4-way alert fan-out) and serves its status over the
rendezvous ``HEALTH`` verb (``reply["slo"]``) for ``obs_top`` and the
item-5 canary verdict. ``tools/slo_report.py`` replays the same
objectives over recorded JSONL/history for offline compliance.
"""

import os
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from tensorflowonspark_tpu.obs import quantiles as quantiles_mod

#: availability SLO target (TOS008); 0 disables the objective
ENV_SLO_AVAILABILITY = "TOS_SLO_AVAILABILITY"
#: TTFT latency objective bound in ms (TOS008); unset/0 disables
ENV_SLO_TTFT_MS = "TOS_SLO_TTFT_MS"
#: end-to-end latency objective bound in ms (TOS008); unset/0 disables
ENV_SLO_E2E_MS = "TOS_SLO_E2E_MS"
#: the quantile latency objectives bound (TOS008)
ENV_SLO_QUANTILE = "TOS_SLO_QUANTILE"
#: burn-rate threshold both windows must exceed to fire (TOS008)
ENV_SLO_BURN = "TOS_SLO_BURN"
#: slow window = this multiple of the fast (detector) window (TOS008)
ENV_SLO_SLOW_MULT = "TOS_SLO_SLOW_MULT"
#: minimum events in the slow window before any verdict (TOS008)
ENV_SLO_MIN_EVENTS = "TOS_SLO_MIN_EVENTS"

_DEFAULT_AVAILABILITY = 0.999
_DEFAULT_QUANTILE = 0.99
_DEFAULT_BURN = 14.4
_DEFAULT_SLOW_MULT = 12.0
_DEFAULT_MIN_EVENTS = 10

#: the availability objective reads the CLIENT boundary. When a fleet
#: fronts the engines (``fleet.submitted`` moving), its counters are the
#: client-visible truth: engine-level ``serve.submitted``/``rejected``
#: count dispatch ATTEMPTS — a retry burst the fleet fully absorbs would
#: read as unavailability, a request that failed over N times would
#: dilute the denominator, and a TOTAL outage (no live replica) never
#: reaches an engine at all, so only fleet counters move. A poisoned
#: fleet request exhausts its failover budget and lands in
#: ``fleet.shed``. Engine-only deployments fall back to the engine tier,
#: where every rejection IS client-visible.
_AVAIL_FLEET_TOTAL = ("fleet.submitted",)
_AVAIL_FLEET_BAD = ("fleet.rejected", "fleet.shed")
_AVAIL_ENGINE_TOTAL = ("serve.submitted",)
_AVAIL_ENGINE_BAD = ("serve.rejected", "serve.poisoned")


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


class Objective(object):
  """One declarative objective, reduced to bad-fraction-over-budget.

  ``kind == "latency"``: at most ``1 − quantile`` of requests may exceed
  ``threshold_ms`` on the merged ``metric`` sketch ("p99 ≤ X" form).
  ``kind == "availability"``: at most ``1 − target`` of submitted
  requests may end shed/rejected/poisoned.
  """

  __slots__ = ("name", "kind", "metric", "threshold_ms", "quantile",
               "target", "budget")

  def __init__(self, name: str, kind: str, metric: Optional[str] = None,
               threshold_ms: Optional[float] = None,
               quantile: float = _DEFAULT_QUANTILE,
               target: Optional[float] = None):
    if kind not in ("latency", "availability"):
      raise ValueError("objective kind must be latency|availability, "
                       "got %r" % (kind,))
    if kind == "latency":
      if not metric or not threshold_ms or threshold_ms <= 0:
        raise ValueError("latency objective %r needs a sketch metric "
                         "and a positive threshold_ms" % name)
      if not 0.5 <= quantile < 1.0:
        raise ValueError("latency quantile must be in [0.5, 1), got %r"
                         % (quantile,))
      budget = 1.0 - quantile
    else:
      if target is None or not 0.0 < target < 1.0:
        raise ValueError("availability objective %r needs a target in "
                         "(0, 1)" % name)
      budget = 1.0 - target
    self.name = name
    self.kind = kind
    self.metric = metric
    self.threshold_ms = None if threshold_ms is None \
        else float(threshold_ms)
    self.quantile = float(quantile)
    self.target = None if target is None else float(target)
    self.budget = budget

  def describe(self) -> dict:
    d = {"name": self.name, "kind": self.kind, "budget": self.budget}
    if self.kind == "latency":
      d.update(metric=self.metric, threshold_ms=self.threshold_ms,
               quantile=self.quantile)
    else:
      d.update(target=self.target)
    return d

  # -- cumulative (total, bad) extraction ------------------------------------

  def totals(self, metrics_by_eid: Dict) -> tuple:
    """``(total_events, bad_events, observed)`` cumulative across the
    cluster right now — two calls subtract into a window (the trick
    that makes sketches windowable: (count, over-count) pairs delta
    even though the sketch itself can't). ``observed`` additionally
    carries the point-in-time view for status displays (the merged
    sketch's current quantile value / the cumulative availability)."""
    if self.kind == "availability":
      def _sum(names):
        acc = 0.0
        for m in metrics_by_eid.values():
          for name in names:
            v = m.get(name)
            if v is not None and "value" in v:
              acc += v["value"]
        return acc

      # fleet tier wins when present (see _AVAIL_* above): the client
      # boundary, immune to retry/failover attempt inflation and live
      # through a total outage
      total = _sum(_AVAIL_FLEET_TOTAL)
      if total > 0:
        bad = _sum(_AVAIL_FLEET_BAD)
      else:
        total = _sum(_AVAIL_ENGINE_TOTAL)
        bad = _sum(_AVAIL_ENGINE_BAD)
      observed = 1.0 - (bad / total) if total > 0 else None
      return total, bad, observed
    merged = quantiles_mod.merge_snapshots(
        [m.get(self.metric) for m in metrics_by_eid.values()])
    total = float(merged.count)
    bad = total - merged.rank(self.threshold_ms) if total else 0.0
    observed = merged.quantile(self.quantile) if total else None
    return total, float(bad), observed


def objectives_from_env() -> List[Objective]:
  """The declared objective set (empty = SLO plane off). Availability
  defaults ON at 99.9% — the serving plane always has an availability
  promise; latency objectives need an explicit bound (nobody can guess
  a deployment's TTFT target)."""
  out: List[Objective] = []
  q = _env_float(ENV_SLO_QUANTILE, _DEFAULT_QUANTILE)
  avail = _env_float(ENV_SLO_AVAILABILITY, _DEFAULT_AVAILABILITY)
  if avail > 0:
    out.append(Objective("availability", "availability", target=avail))
  ttft = _env_float(ENV_SLO_TTFT_MS, 0.0)
  if ttft > 0:
    out.append(Objective("ttft_p%g" % (100 * q), "latency",
                         metric="serve.ttft_ms", threshold_ms=ttft,
                         quantile=q))
  e2e = _env_float(ENV_SLO_E2E_MS, 0.0)
  if e2e > 0:
    out.append(Objective("e2e_p%g" % (100 * q), "latency",
                         metric="serve.e2e_ms", threshold_ms=e2e,
                         quantile=q))
  return out


class SLOTracker(object):
  """Rolling multi-window burn-rate evaluation over cumulative samples.

  Driven by the detector loop: :meth:`sample` appends one cumulative
  ``(t, total, bad)`` point per objective from the sink's per-executor
  metric state; :meth:`evaluate` subtracts window edges into fast/slow
  bad-fractions and returns one verdict dict per objective —
  ``verdict["burning"]`` is the ``slo_burn`` trigger. No waits, no
  threads: the caller owns cadence (and its own locking).
  """

  def __init__(self, objectives: Optional[Sequence[Objective]] = None,
               window: float = 20.0,
               slow_mult: Optional[float] = None,
               burn_threshold: Optional[float] = None,
               min_events: Optional[int] = None):
    self.objectives = list(objectives if objectives is not None
                           else objectives_from_env())
    self.window = float(window)
    self.slow_mult = max(1.0, slow_mult if slow_mult is not None
                         else _env_float(ENV_SLO_SLOW_MULT,
                                         _DEFAULT_SLOW_MULT))
    self.burn_threshold = float(
        burn_threshold if burn_threshold is not None
        else _env_float(ENV_SLO_BURN, _DEFAULT_BURN))
    self.min_events = int(min_events if min_events is not None
                          else _env_float(ENV_SLO_MIN_EVENTS,
                                          _DEFAULT_MIN_EVENTS))
    self.slow_window = self.window * self.slow_mult
    # per-objective deque of (t, total, bad); retention covers the slow
    # window plus one pre-window baseline sample
    self._series: Dict[str, deque] = {
        o.name: deque(maxlen=8192) for o in self.objectives}
    self._observed: Dict[str, Optional[float]] = {}

  def __bool__(self) -> bool:
    return bool(self.objectives)

  # -- sampling --------------------------------------------------------------

  def sample(self, now: float, metrics_by_eid: Dict) -> None:
    """Append one cumulative sample per objective from the sink's
    ``{eid: {metric: snapshot}}`` state."""
    for obj in self.objectives:
      total, bad, observed = obj.totals(metrics_by_eid)
      dq = self._series[obj.name]
      dq.append((now, total, bad))
      self._observed[obj.name] = observed
      # retire samples past the slow window, keeping one baseline
      while len(dq) >= 2 and dq[1][0] <= now - self.slow_window:
        dq.popleft()

  @staticmethod
  def _window_frac(dq, now: float, window: float):
    """(bad_fraction, events) across the window ending at ``now`` —
    deltas between the newest sample and the newest sample at/before
    the window edge (or the oldest retained as baseline)."""
    if len(dq) < 2:
      return None, 0.0
    edge = now - window
    base = dq[0]
    for rec in dq:
      if rec[0] <= edge:
        base = rec
      else:
        break
    t1, total1, bad1 = dq[-1]
    dt_total = total1 - base[1]
    dt_bad = bad1 - base[2]
    if dt_total <= 0:
      return None, 0.0
    return max(0.0, dt_bad) / dt_total, dt_total

  # -- evaluation ------------------------------------------------------------

  def evaluate(self, now: float) -> List[dict]:
    """One verdict per objective (msgpack/json-safe). ``burning`` is
    True when BOTH windows' burn rates are at/over the threshold with
    enough events in the slow window to mean anything."""
    out = []
    for obj in self.objectives:
      dq = self._series[obj.name]
      frac_fast, n_fast = self._window_frac(dq, now, self.window)
      frac_slow, n_slow = self._window_frac(dq, now, self.slow_window)
      burn_fast = None if frac_fast is None \
          else frac_fast / obj.budget
      burn_slow = None if frac_slow is None \
          else frac_slow / obj.budget
      burning = (burn_fast is not None and burn_slow is not None
                 and n_slow >= self.min_events
                 and burn_fast >= self.burn_threshold
                 and burn_slow >= self.burn_threshold)
      v = dict(obj.describe(),
               observed=self._observed.get(obj.name),
               bad_frac_fast=frac_fast, bad_frac_slow=frac_slow,
               events_fast=n_fast, events_slow=n_slow,
               burn_fast=burn_fast, burn_slow=burn_slow,
               window_fast=self.window, window_slow=self.slow_window,
               burn_threshold=self.burn_threshold, burning=burning)
      out.append(v)
    return out

  def status(self, now: Optional[float] = None) -> dict:
    """The HEALTH-wire SLO payload: per-objective verdicts + the window
    geometry (msgpack-safe; floats and bools only)."""
    if now is None:
      now = time.monotonic()
    return {"objectives": self.evaluate(now),
            "window_fast": self.window, "window_slow": self.slow_window,
            "burn_threshold": self.burn_threshold}
