"""Compile/device telemetry tier: recompile sentinel, HLO cost, memory.

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md) identifies compile-time and step-time variance as the
dominant at-scale failure signals; a recompile storm (a jit seam whose
cache keys on data-dependent shapes) silently multiplies step time by
the compile cost. This module feeds those signals into the SAME process
registry the rest of the obs plane ships driver-ward, so the anomaly
detectors (``obs.anomaly``) and the live monitor (``tools/obs_top.py``)
see them online instead of post-mortem:

- **Recompile sentinel** — :func:`install_compile_listener` hooks
  ``jax.monitoring``'s backend-compile duration events (where this jax
  exposes them) into ``xla.compiles`` / ``xla.compile_ms`` plus one
  retroactive ``compile`` span per compilation. Per-function labels
  come from :func:`note_trace` calls placed INSIDE our own jit seams
  (``models/transformer.py`` decode loops, ``serving/slots.py`` slab
  ops, ``parallel/sharding.py`` train step): jit re-traces the Python
  body exactly once per new cache entry, so a trace count is a compile
  count per seam (``xla.compiles.<label>``; an explicit ``.lower()``
  retraces too — the cost-capture path below is the only caller).
- **HLO cost capture** — :func:`capture_cost` runs
  ``jitted.lower(*args).cost_analysis()`` once per (label, arg-shape
  fingerprint) and records ``xla.cost.<label>.flops`` /
  ``xla.cost.<label>.bytes`` gauges, so the roofline-relevant numbers
  for the train and serving steps ride the OBS wire.
- **Device-memory gauges** — :func:`make_memory_sampler` folds
  ``obs.profiler.device_memory_stats`` (exported API that previously
  nothing sampled) into ``device.bytes_in_use`` / ``device.peak_bytes``
  / ``device.bytes_limit`` gauges; ``node._start_obs_shipper`` runs it
  on the ObsShipper cadence so watermarks ship with every delta.

Everything honors the plane's invariant: zero work when ``TOS_OBS=0``
(callers guard on :func:`metrics.active`), failures counted not raised,
and the listener/sampler hot paths are a few GIL-guarded updates per
COMPILE or per SHIP — never per step. ``TOS_OBS_DEVICE=0`` switches
just this tier off while the rest of the plane keeps running.
"""

import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from tensorflowonspark_tpu.obs import metrics as metrics_mod
from tensorflowonspark_tpu.obs import spans as spans_mod

logger = logging.getLogger(__name__)

#: device/compile tier gate — default ON whenever ``TOS_OBS=1``; set to
#: ``0`` to keep the base plane without the jax.monitoring hook and
#: memory sampler (env registry: TOS008)
ENV_OBS_DEVICE = "TOS_OBS_DEVICE"

#: compile durations are ms-to-minutes: dedicated wide bucket bounds
COMPILE_MS_BUCKETS = (1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0,
                      5000.0, 15000.0, 60000.0, 300000.0)

#: the jax.monitoring duration event one backend compilation emits
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: the jax.monitoring instant event one PERSISTENT-cache hit emits
#: (TOS_COMPILE_CACHE, node._setup_compile_cache). NOTE: jax's
#: ``_COMPILE_EVENT`` duration event WRAPS compile_or_get_cached, so it
#: fires on hits too — this instant event fires INSIDE that region, and
#: each one arms a ``_pending_hits`` discount that absorbs its paired
#: duration event. Net effect: hits surface as ``xla.cache_hits`` and
#: never count as fresh compiles (the recompile-storm detector must not
#: treat a relaunched executor's warm loads as a storm)
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_install_lock = threading.Lock()
_monitoring_hooked = False
#: persistent-cache hits whose enclosing backend-compile duration event
#: has not arrived yet: jax's duration event WRAPS compile_or_get_cached,
#: so it fires for cache hits too — each hit arms one discount so the
#: paired duration event is counted as a load, not a fresh compile
_pending_hits = {"n": 0}
_pending_lock = threading.Lock()
_cost_seen: set = set()
_cost_lock = threading.Lock()
#: sentinel-internal failures (counted, never raised — the tier must not
#: poison the compile/trace it observes); mutable dict, not a bare int,
#: so the hot-path handlers can count without `global`
SENTINEL_ERRORS = {"count": 0}


def device_tier_enabled() -> bool:
  """True when the obs plane is on AND the device tier isn't opted out."""
  return metrics_mod.enabled() and \
      os.environ.get(ENV_OBS_DEVICE, "1") not in ("0",)


# -- recompile sentinel -------------------------------------------------------


def _on_compile_duration(event: str, duration: float, **kwargs) -> None:
  """jax.monitoring listener: one backend compile happened somewhere in
  this process. Looks the registry up at EVENT time (listeners are
  process-global and outlive any one registry), so with the plane off
  this is one None check per compile — and compiles are rare."""
  if event != _COMPILE_EVENT:
    return
  with _pending_lock:
    if _pending_hits["n"] > 0:
      # this "compile" was a persistent-cache load (the hit event fired
      # inside the wrapped lookup): already counted as xla.cache_hits,
      # must not count as a fresh compile or relaunched executors with a
      # warm TOS_COMPILE_CACHE read as a recompile storm
      _pending_hits["n"] -= 1
      return
  reg = metrics_mod.active()
  if reg is None:
    return
  try:
    reg.counter("xla.compiles").inc()
    reg.histogram("xla.compile_ms", COMPILE_MS_BUCKETS).observe(
        duration * 1e3)
    rec = spans_mod.active()
    if rec is not None:
      # retroactive span: the event fires when the compile ENDS
      rec.record_span("compile", time.monotonic() - duration, duration)
  except Exception:  # noqa: BLE001 - telemetry must never break a compile
    SENTINEL_ERRORS["count"] += 1


def _on_event(event: str, **kwargs) -> None:
  """jax.monitoring instant-event listener: persistent-cache hits.

  Each hit also arms one compile-duration discount (``_pending_hits``)
  — the hit fires INSIDE the duration-event region, so the discount is
  armed before the duration event it must absorb."""
  if event != _CACHE_HIT_EVENT:
    return
  with _pending_lock:
    _pending_hits["n"] += 1
  reg = metrics_mod.active()
  if reg is None:
    return
  try:
    reg.counter("xla.cache_hits").inc()
  except Exception:  # noqa: BLE001 - telemetry must never break a load
    SENTINEL_ERRORS["count"] += 1


def install_compile_listener() -> bool:
  """Hook jax.monitoring's compile events into the registry (idempotent).

  Two listeners: backend-compile durations → ``xla.compiles`` (fresh
  compiles only — the duration event wraps jax's cache lookup and fires
  on persistent-cache hits too, so each hit's instant event arms a
  discount that absorbs its paired duration event) and cache-hit
  instants → ``xla.cache_hits``.
  Returns True when the hooks are (already) installed; False when this
  jax has no usable ``jax.monitoring`` — :func:`note_trace` then counts
  the global ``xla.compiles`` from our own seams as the fallback.
  """
  global _monitoring_hooked
  with _install_lock:
    if _monitoring_hooked:
      return True
    try:
      from jax import monitoring
      monitoring.register_event_duration_secs_listener(_on_compile_duration)
      monitoring.register_event_listener(_on_event)
    except Exception as e:  # noqa: BLE001 - older jax / stub backends:
      # the tracing-counter fallback still covers our own seams
      logger.info("jax.monitoring unavailable (%s); recompile sentinel "
                  "falls back to per-seam trace counters", e)
      return False
    _monitoring_hooked = True
    return True


def monitoring_hooked() -> bool:
  return _monitoring_hooked


def note_trace(label: str) -> None:
  """Call at the TOP of a jit-compiled function body: fires once per
  (re)trace — i.e. once per new jit-cache entry — giving the recompile
  sentinel its per-function labels (``xla.compiles.<label>``).

  Host-side effect at trace time by design (the traced computation never
  contains it). When ``jax.monitoring`` is absent the seam also counts
  the global ``xla.compiles`` so the storm detector stays armed.
  """
  reg = metrics_mod.active()
  if reg is None:
    return
  try:
    reg.counter("xla.compiles." + label).inc()
    if not _monitoring_hooked:
      reg.counter("xla.compiles").inc()
    rec = spans_mod.active()
    if rec is not None:
      rec.event("compile.trace", label=label)
  except Exception:  # noqa: BLE001 - a telemetry bug must not poison a trace
    SENTINEL_ERRORS["count"] += 1


# -- HLO cost capture ---------------------------------------------------------


def _shape_fingerprint(args, kwargs) -> str:
  """Stable (shape, dtype) fingerprint of a jitted call's arguments."""
  import jax
  parts = []
  for leaf in jax.tree.leaves((args, kwargs)):
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None:
      parts.append(type(leaf).__name__)
    else:
      parts.append("%s%s" % (dtype, list(shape)))
  return ";".join(parts)


def capture_cost(label: str, jitted_fn, *args, **kwargs) -> Optional[dict]:
  """Record ``lowered.cost_analysis()`` flops/bytes for one jitted seam,
  once per distinct argument-shape fingerprint.

  Gauges: ``xla.cost.<label>.flops`` and ``xla.cost.<label>.bytes``
  (bytes accessed), plus an ``xla.cost.captures`` counter. The lowering
  retraces the function (bumping its :func:`note_trace` counter once —
  the only non-compile caller); failures are counted into
  ``xla.cost.failures`` and never raised. Returns the captured dict, or
  None (disabled / already seen / analysis unavailable).
  """
  reg = metrics_mod.active()
  # gate on the live registry (explicit activation counts — tests,
  # embedders) plus the tier opt-out, not on the TOS_OBS env alone
  if reg is None or os.environ.get(ENV_OBS_DEVICE, "1") in ("0",):
    return None
  key = (label, _shape_fingerprint(args, kwargs))
  with _cost_lock:
    if key in _cost_seen:
      return None
    _cost_seen.add(key)
  try:
    cost = jitted_fn.lower(*args, **kwargs).cost_analysis()
    # jax has returned both a dict and a per-device list of dicts
    if isinstance(cost, (list, tuple)):
      cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0) or 0.0)
    nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    reg.gauge("xla.cost.%s.flops" % label).set(flops)
    reg.gauge("xla.cost.%s.bytes" % label).set(nbytes)
    reg.counter("xla.cost.captures").inc()
    rec = spans_mod.active()
    if rec is not None:
      rec.event("compile.cost", label=label, flops=flops, bytes=nbytes)
    return {"flops": flops, "bytes": nbytes}
  except Exception as e:  # noqa: BLE001 - cost analysis is best-effort
    # telemetry (backends without HLO properties, AOT-only paths)
    reg.counter("xla.cost.failures").inc()
    logger.debug("cost capture for %r failed: %s", label, e)
    return None


def reset_cost_cache() -> None:
  """Forget per-process cost fingerprints (test isolation helper)."""
  with _cost_lock:
    _cost_seen.clear()


# -- device-memory gauges -----------------------------------------------------


def make_memory_sampler(registry: metrics_mod.MetricsRegistry,
                        stats_fn: Optional[Callable[[], Dict]] = None
                        ) -> Callable[[], None]:
  """A sampler closure for :meth:`ObsShipper.add_sampler`: reads
  ``device_memory_stats`` and sets process-wide watermark gauges.

  ``device.bytes_in_use`` / ``device.bytes_limit`` sum across this
  process's local devices (the footprint that OOMs together);
  ``device.peak_bytes`` is the max single-device peak (the first chip to
  hit its limit is the one that kills the step). Backends that report no
  memory stats (typical CPU) leave the gauges untouched — the sampler
  stays a cheap no-op.
  """
  if stats_fn is None:
    from tensorflowonspark_tpu.obs import profiler
    stats_fn = profiler.device_memory_stats
  g_use = registry.gauge("device.bytes_in_use")
  g_peak = registry.gauge("device.peak_bytes")
  g_limit = registry.gauge("device.bytes_limit")
  c_samples = registry.counter("device.mem_samples")
  last = {}

  def sample() -> None:
    stats = stats_fn()
    if not stats:
      return
    in_use = sum(d.get("bytes_in_use", 0) for d in stats.values())
    peak = max((d.get("peak_bytes_in_use", 0) for d in stats.values()),
               default=0)
    limit = sum(d.get("bytes_limit", 0) for d in stats.values())
    cur = (in_use, peak, limit)
    if last.get("v") == cur:
      # static memory on an idle executor: touch NOTHING, or the
      # per-round counter bump alone would wake the shipper's wire
      # every interval forever (the idle short-circuit's whole point)
      return
    last["v"] = cur
    g_use.set(in_use)
    if peak:
      g_peak.set(peak)
    if limit:
      g_limit.set(limit)
    c_samples.inc()

  return sample


def install(shipper=None) -> bool:
  """Bring the whole device tier up for this process (idempotent).

  Installs the compile listener; when a ``shipper`` is given, registers
  the memory sampler on its cadence so the gauges ride every delta.
  No-op (False) when the tier is disabled.
  """
  if not device_tier_enabled():
    return False
  install_compile_listener()
  if shipper is not None and shipper.registry is not None:
    shipper.add_sampler(make_memory_sampler(shipper.registry))
  return True
