"""Profiling/tracing helpers: the JAX-native TensorBoard story.

The reference's only tracing facility was launching TensorBoard as a
subprocess on chief/worker:0 (reference TFSparkNode.py:292-329 — that part
lives in node.py here). This module adds what TPU users actually profile
with: the JAX profiler — a programmatic trace context writing XProf/
perfetto data TensorBoard can render, and an on-demand capture server.

Moved from ``utils/profiler.py`` into the observability plane (``obs/``):
:class:`StepTimer` now doubles as the training loop's seam into the
metrics registry — when the obs plane is active (``TOS_OBS=1``) each
timed step also lands a ``train.step_ms`` histogram observation, a
``train.steps``/``train.items`` counter bump and a ``train.step`` span,
so the step loop shows up in the shipped deltas and the merged Chrome
trace without any extra user code. The old import path keeps working via
a deprecation shim.
"""

import contextlib
import logging
import os
import time
from typing import Optional

from tensorflowonspark_tpu.obs import metrics as metrics_mod
from tensorflowonspark_tpu.obs import spans as spans_mod

logger = logging.getLogger(__name__)

_server = None


def start_server(port: int = 9999):
  """Start the JAX profiler capture server (connect with TensorBoard's
  profile tab or `xprof`); idempotent per process."""
  global _server
  if _server is None:
    import jax
    _server = jax.profiler.start_server(port)
    logger.info("JAX profiler server listening on port %d", port)
  return _server


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2):
  """Trace a region into ``log_dir`` (viewable in TensorBoard).

  Usage::

      with profiler.trace("/tmp/tb"):
          state, loss = train_step(state, batch)
          jax.block_until_ready(loss)
  """
  import jax
  os.makedirs(log_dir, exist_ok=True)
  with jax.profiler.trace(log_dir):
    yield
  logger.info("profile trace written to %s", log_dir)


def annotate(name: str):
  """Named region annotation for traces (shows up on the timeline)."""
  import jax
  return jax.profiler.TraceAnnotation(name)


# --- step timing / throughput ------------------------------------------------


class StepTimer(object):
  """Wall-clock step statistics with warmup exclusion.

  Usage::

      timer = StepTimer(warmup=2)
      for batch in data:
          with timer.step(items=batch_size):
              state, loss = train_step(state, batch)
              jax.block_until_ready(loss)
      print(timer.summary())   # {steps, mean_ms, p50_ms, p90_ms, items/s}

  The context manager blocks on nothing itself — callers must
  ``block_until_ready`` inside the region or the async dispatch makes every
  step look instant.

  When a metrics registry is active (``obs.metrics.active()``), every
  post-warmup step additionally feeds the registry (``train.steps``,
  ``train.items``, ``train.step_ms``) and records a ``train.step`` span.
  """

  def __init__(self, warmup: int = 2):
    self.warmup = warmup
    self._durations = []
    self._items = []
    self._seen = 0
    # cached once: the step context is the training hot path, and the
    # disabled case must stay a None check; metric HANDLES are cached
    # too (registry get-or-create takes a lock — setup cost, not step
    # cost)
    self._reg = metrics_mod.active()
    self._rec = spans_mod.active()
    if self._reg is not None:
      self._m_steps = self._reg.counter("train.steps")
      self._m_items = self._reg.counter("train.items")
      self._m_step_ms = self._reg.histogram("train.step_ms")

  @contextlib.contextmanager
  def step(self, items: int = 0):
    t0 = time.perf_counter()
    mono0 = time.monotonic() if self._rec is not None else 0.0
    yield
    dt = time.perf_counter() - t0
    self._seen += 1
    if self._seen > self.warmup:
      self._durations.append(dt)
      self._items.append(items)
      if self._reg is not None:
        self._m_steps.inc()
        if items:
          self._m_items.inc(items)
        self._m_step_ms.observe(dt * 1e3)
      if self._rec is not None:
        self._rec.record_span("train.step", mono0, dt, items=items)

  def summary(self) -> dict:
    d = sorted(self._durations)
    if not d:
      return {"steps": 0}
    total = sum(self._durations)
    out = {
        "steps": len(d),
        "mean_ms": 1e3 * total / len(d),
        "p50_ms": 1e3 * d[len(d) // 2],
        "p90_ms": 1e3 * d[min(len(d) - 1, int(len(d) * 0.9))],
    }
    if any(self._items):
      out["items_per_sec"] = sum(self._items) / total
    return out


# --- MFU accounting ----------------------------------------------------------

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets)
PEAK_BF16_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}


def resolve_chip_generation(hint: str = "") -> Optional[str]:
  """Map a generation hint / device_kind string to a PEAK_BF16_FLOPS key."""
  text = (hint or "").lower()
  for alias, g in (("v5 lite", "v5e"), ("v5lite", "v5e"), ("v6 lite", "v6e"),
                   ("v6lite", "v6e")):
    if alias in text:
      return g
  # longest key first so "v5p" isn't shadowed by a hypothetical "v5"
  for g in sorted(PEAK_BF16_FLOPS, key=len, reverse=True):
    if g in text:
      return g
  return None


def transformer_flops_per_token(n_params: int, num_layers: int,
                                d_model: int, seq_len: int) -> float:
  """Training FLOPs/token, PaLM-style accounting: ``6N`` for the fwd+bwd
  matmuls plus the attention term ``12·L·d_model·S``."""
  return 6.0 * n_params + 12.0 * num_layers * d_model * seq_len


def mfu(flops_per_item: float, items_per_sec: float,
        peak_flops: float) -> float:
  """Model FLOPs utilization against one chip's peak."""
  return flops_per_item * items_per_sec / peak_flops


def device_memory_stats() -> dict:
  """Per-device memory stats (bytes) where the backend reports them."""
  import jax
  out = {}
  for d in jax.devices():
    stats = getattr(d, "memory_stats", lambda: None)()
    if stats:
      out[str(d.id)] = {k: stats[k] for k in
                        ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                        if k in stats}
  return out
