"""Driver-side aggregation + executor-side shipping for the obs plane.

Executors ship bounded metric/span DELTAS to the driver through a new
rendezvous verb, ``OBS`` (control/rendezvous.py): the
:class:`ObsShipper` thread snapshots the process registry, subtracts the
last acknowledged snapshot, drains a bounded batch of spans, and sends
one msgpack message per interval. The server hands the message to the
:class:`ObsSink` the driver attached (``Server.obs_sink``); without a
sink the verb is acknowledged and dropped — observability is never a
prerequisite for the control plane.

Failure policy (TOS001 end to end):

- every wait is timeout-bounded; the ship socket rides a short-deadline
  rendezvous ``Client``;
- a failed ship NEVER raises into the instrumented process: the metric
  delta is retried next interval (the baseline snapshot only advances on
  ack), the drained spans are counted into ``spans_lost`` and given up —
  bounded memory beats completeness;
- the sink's span buffer is bounded; overflow increments a drop counter
  that the report surfaces.

The shipper also appends its drained spans to a per-process JSONL file
when ``TOS_OBS_DIR`` is set (``obs.export``), so the offline
Chrome-trace plane works even for processes the driver never hears from.
"""

import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from tensorflowonspark_tpu.obs import metrics as metrics_mod
from tensorflowonspark_tpu.obs import spans as spans_mod

logger = logging.getLogger(__name__)

#: seconds between OBS ship rounds (env registry: TOS008)
ENV_OBS_INTERVAL = "TOS_OBS_INTERVAL"
#: max spans per OBS message (bounds the wire frame; TOS008)
ENV_OBS_SHIP_SPANS = "TOS_OBS_SHIP_SPANS"
#: driver-side sink span-buffer capacity (TOS008)
ENV_OBS_SINK_SPANS = "TOS_OBS_SINK_SPANS"

_DEFAULT_INTERVAL = 2.0
_DEFAULT_SHIP_SPANS = 512
_DEFAULT_SINK_SPANS = 65536


class ObsShipper(object):
  """Background thread shipping metric/span deltas via the OBS verb."""

  def __init__(self, server_addr: Tuple[str, int], executor_id: int,
               registry: Optional[metrics_mod.MetricsRegistry] = None,
               recorder: Optional[spans_mod.SpanRecorder] = None,
               clock: Optional[spans_mod.ClockOffset] = None,
               interval: Optional[float] = None, label: str = "executor",
               jsonl_dir: Optional[str] = None):
    self.server_addr = (server_addr[0], int(server_addr[1]))
    self.executor_id = int(executor_id)
    self.label = label
    self.registry = registry if registry is not None else metrics_mod.active()
    self.recorder = recorder if recorder is not None else spans_mod.active()
    # the clock may be SHARED with the HeartbeatSender (the BEAT piggyback
    # is usually the higher-frequency sampler); OBS replies feed it too
    self.clock = clock if clock is not None else (
        self.recorder.clock if self.recorder is not None
        else spans_mod.ClockOffset())
    if interval is None:
      interval = float(os.environ.get(ENV_OBS_INTERVAL,
                                      str(_DEFAULT_INTERVAL)))
    self.interval = max(0.05, interval)
    self.max_spans = int(os.environ.get(ENV_OBS_SHIP_SPANS,
                                        str(_DEFAULT_SHIP_SPANS)))
    from tensorflowonspark_tpu.obs import export as export_mod
    self._jsonl = export_mod.ProcessLog(
        jsonl_dir, label=label, executor_id=self.executor_id,
        clock=self.clock)
    self._client = None
    # baseline = NOW: ships deltas accrued since this shipper started. A
    # persistent FILES-mode executor reuses one process registry across
    # cluster runs; an empty baseline would re-ship the previous run's
    # totals into the next run's sink as fresh increments.
    self._last_acked: Dict[str, dict] = (
        self.registry.snapshot() if self.registry is not None else {})
    self._seq = 0
    self.ship_failures = 0
    self.ships_acked = 0
    self.spans_lost = 0
    self.sampler_failures = 0
    # pre-ship samplers (device-memory watermarks, …): run once per ship
    # round so gauges ride the normal delta wire on the shipper cadence
    self._samplers: List = []
    self._clock_gauges = None
    self._clock_last = None
    # serializes ship/obs_send (and the client teardown in stop) against
    # the loop thread: stop() joins with a TIMEOUT, so the final flush
    # can overlap a wedged in-flight ship and race _seq/_last_acked/
    # _client. RLock: ship() holds it across its obs_send() call.
    self._ship_lock = threading.RLock()
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None

  def add_sampler(self, fn) -> None:
    """Register a zero-arg callable run before every ship's snapshot
    (``obs.device.make_memory_sampler`` is the canonical one). Sampler
    exceptions are counted (``sampler_failures``), never raised."""
    self._samplers.append(fn)

  def _run_samplers(self) -> None:
    for fn in self._samplers:
      try:
        fn()
      except Exception:  # noqa: BLE001 - a broken sampler must not stop
        # the metric deltas that do work from shipping
        self.sampler_failures += 1
    if self.registry is not None:
      # clock-offset QUALITY rides the registry too (satellite of the
      # device tier): rtt_ms bounds the offset error (±rtt/2), samples
      # counts the TIME exchanges feeding the estimate — surfaced in
      # Prometheus exposition and obs_report without ad-hoc plumbing.
      # Gauges only move when the ELECTED estimate moves: every acked
      # ship is itself a TIME exchange, so per-sample updates would ship
      # a delta every round and the idle-wire short-circuit could never
      # fire again.
      snap = self.clock.snapshot()
      if snap["samples"] and \
          (snap["offset"], snap["rtt"]) != self._clock_last:
        self._clock_last = (snap["offset"], snap["rtt"])
        if self._clock_gauges is None:
          self._clock_gauges = (self.registry.gauge("clock.offset_ms"),
                                self.registry.gauge("clock.rtt_ms"),
                                self.registry.gauge("clock.samples"))
        self._clock_gauges[0].set(snap["offset"] * 1e3)
        self._clock_gauges[1].set((snap["rtt"] or 0.0) * 1e3)
        self._clock_gauges[2].set(snap["samples"])

  # -- wire ------------------------------------------------------------------

  def _ensure_client(self):
    if self._client is None:
      from tensorflowonspark_tpu.control import rendezvous
      # short deadline: a ship that cannot land within ~2 intervals is
      # stale anyway, and the final flush must never stall teardown
      self._client = rendezvous.Client(
          self.server_addr, timeout=max(0.5, min(5.0, 2 * self.interval)))
    return self._client

  def obs_send(self, msg: dict, timeout: float) -> Optional[dict]:
    """One OBS request/ack round-trip, deadline-bounded; None on failure.

    Named into the analyzer's blocking-verb set (TOS001): callers must
    pass an explicit ``timeout``.
    """
    with self._ship_lock:
      return self._obs_send_locked(msg, timeout)

  def _obs_send_locked(self, msg: dict, timeout: float) -> Optional[dict]:
    t0 = time.monotonic()
    try:
      client = self._ensure_client()
      client.timeout = max(0.5, float(timeout))
      resp = client._request(msg)
    except Exception as e:  # noqa: BLE001 - the obs plane must never take
      # down the process it observes; failures are counted, not raised
      self.ship_failures += 1
      if self.ship_failures == 1:
        logger.warning("obs ship to %s failing: %s", self.server_addr, e)
      if self._client is not None:
        self._client.close()
        self._client = None
      return None
    t1 = time.monotonic()
    if resp.get("dropped"):          # chaos-injected message loss
      self.ship_failures += 1
      return None
    if "server_time" in resp:
      # even a rejected ship is a valid TIME exchange
      self.clock.update(t0, resp["server_time"], t1)
    if resp.get("accepted") is False:
      # the server answered but the sink rejected/was absent: NOT an ack
      # — the caller must keep its metrics baseline so deltas retry
      self.ship_failures += 1
      return None
    return resp

  # -- shipping --------------------------------------------------------------

  def ship(self, timeout: Optional[float] = None) -> bool:
    """Snapshot, subtract, drain, send. True when the driver acked."""
    if timeout is None:
      timeout = max(0.5, 2 * self.interval)
    with self._ship_lock:
      return self._ship_locked(timeout)

  def _ship_locked(self, timeout: float) -> bool:
    self._run_samplers()
    cur = self.registry.snapshot() if self.registry is not None else {}
    delta = metrics_mod.snapshot_delta(cur, self._last_acked)
    spans: List[dict] = []
    if self.recorder is not None:
      spans = self.recorder.drain(self.max_spans)
      self._jsonl.append_spans(spans)
    drops = dict(self.recorder.drop_counts()) if self.recorder is not None \
        else {}
    drops["spans_lost"] = self.spans_lost
    drops["ship_failures"] = self.ship_failures
    if not spans and self.ships_acked > 0 and \
        all(k.startswith("clock.") for k in delta):
      # idle: nothing to say, keep the wire quiet. Clock-quality gauges
      # alone never wake the wire — every acked ship is a TIME exchange,
      # so they'd otherwise ship a delta forever; they piggyback on the
      # next real delta instead (the baseline deliberately not advanced)
      return True
    self._seq += 1
    msg = {"type": "OBS", "executor_id": self.executor_id,
           "label": self.label, "pid": os.getpid(), "seq": self._seq,
           "metrics": delta, "spans": spans, "drops": drops,
           "clock": self.clock.snapshot()}
    resp = self.obs_send(msg, timeout=timeout)
    if resp is None:
      # metrics retry next round (baseline unchanged); spans are gone —
      # counted, so the loss is visible in the next successful ship
      self.spans_lost += len(spans)
      return False
    self._last_acked = cur
    self.ships_acked += 1
    return True

  def _run(self) -> None:
    while not self._stop.wait(self.interval):
      self.ship()

  def start(self) -> "ObsShipper":
    self._thread = threading.Thread(
        target=self._run, daemon=True,
        name="tos-obs-shipper-%d" % self.executor_id)
    self._thread.start()
    return self

  def stop(self, timeout: float = 5.0) -> None:
    """Stop the thread, final-flush (bounded), close the socket and the
    JSONL log (stamping the final clock offset + registry snapshot)."""
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=timeout)
    self.ship(timeout=min(2.0, timeout))
    final = self.registry.snapshot() if self.registry is not None else {}
    if self.recorder is not None:
      self._jsonl.append_spans(self.recorder.drain(None))
    self._jsonl.close(metrics_snapshot=final)
    with self._ship_lock:
      if self._client is not None:
        self._client.close()
        self._client = None


class ObsSink(object):
  """Driver-side accumulator fed by the rendezvous OBS handler.

  Per-executor metric totals (deltas re-applied), a bounded span buffer,
  clock/drop bookkeeping. ``ingest`` runs on the rendezvous serve thread:
  it must stay cheap, bounded, and exception-free.
  """

  def __init__(self, max_spans: Optional[int] = None):
    if max_spans is None:
      max_spans = int(os.environ.get(ENV_OBS_SINK_SPANS,
                                     str(_DEFAULT_SINK_SPANS)))
    self.max_spans = max(1, max_spans)
    self._cond = threading.Condition()
    self._spans: deque = deque()
    self.spans_dropped = 0
    self.executors: Dict[int, dict] = {}
    self.ingested = 0
    self.rejected = 0

  # -- ingestion (rendezvous serve thread) -----------------------------------

  def ingest(self, msg: dict) -> bool:
    try:
      eid = int(msg["executor_id"])
      delta = msg.get("metrics") or {}
      spans = msg.get("spans") or []
    except Exception:  # noqa: BLE001 - malformed OBS payloads are counted
      # and dropped; the serve loop (and the sender) must not care
      self.rejected += 1
      return False
    clock = msg.get("clock") or {}
    offset = float(clock.get("offset") or 0.0)
    with self._cond:
      entry = self.executors.setdefault(
          eid, {"metrics": {}, "clock": {}, "drops": {}, "ships": 0,
                "label": msg.get("label"), "pid": msg.get("pid")})
      metrics_mod.apply_delta(entry["metrics"], delta)
      entry["clock"] = clock
      entry["drops"] = msg.get("drops") or {}
      entry["ships"] += 1
      entry["label"] = msg.get("label") or entry["label"]
      entry["pid"] = msg.get("pid") or entry["pid"]
      entry["last_seen"] = time.monotonic()
      for rec in spans:
        if len(self._spans) >= self.max_spans:
          self.spans_dropped += 1
          continue
        out = dict(rec)
        out["executor_id"] = eid
        out["offset"] = offset
        self._spans.append(out)
      self.ingested += 1
      if spans:
        self._cond.notify_all()
    return True

  # -- read plane ------------------------------------------------------------

  def obs_recv(self, max_items: int = 256, block: bool = True,
               timeout: Optional[float] = None) -> List[dict]:
    """Pop up to ``max_items`` collected spans (driver-anchorable: each
    carries the shipper's clock ``offset``). Named into the analyzer's
    blocking-verb set (TOS001): blocking callers pass a ``timeout``.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._cond:
      while not self._spans:
        if not block:
          return []
        remaining = None if deadline is None \
            else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          return []
        self._cond.wait(timeout=0.25 if remaining is None
                        else min(remaining, 0.25))
      out = []
      for _ in range(min(max_items, len(self._spans))):
        out.append(self._spans.popleft())
      return out

  def metrics(self, executor_id: Optional[int] = None) -> Dict:
    """One executor's cumulative metric totals, or all of them."""
    with self._cond:
      if executor_id is not None:
        entry = self.executors.get(int(executor_id))
        return dict(entry["metrics"]) if entry else {}
      return {eid: dict(e["metrics"]) for eid, e in self.executors.items()}

  def aggregate(self, name: str) -> float:
    """Sum one counter/gauge across executors (0.0 when absent)."""
    total = 0.0
    with self._cond:
      for e in self.executors.values():
        m = e["metrics"].get(name)
        if m and "value" in m:
          total += m["value"]
    return total

  #: the compact metric set the HEALTH verb / obs_top surface per
  #: executor: cumulative counters the poller turns into rates, plus the
  #: last-written gauges. Bounded and msgpack-safe by construction.
  TOP_METRICS = (
      "train.steps", "train.items",
      "feed.batches", "feed.rows", "feed.fetch_s", "feed.decode_s",
      "feed.assemble_s",
      "serve.tokens", "serve.completed", "serve.occupancy",
      "serve.queue_depth", "serve.slots_active",
      "serve.rejected", "serve.expired", "serve.cancelled",
      "serve.replays", "serve.engine_restarts",
      "xla.compiles",
      "device.bytes_in_use", "device.peak_bytes", "device.bytes_limit",
      "clock.offset_ms", "clock.rtt_ms", "clock.samples",
      "feed.autotune_moves",
      "obs.alerts",
  )

  #: dynamic-name metric families the summary also carries: the datapipe
  #: executor's per-stage gauges (one small set per declared graph stage
  #: — bounded by the graph, which is operator-declared)
  TOP_METRIC_PREFIXES = ("feed.stage.",)

  def top_summary(self) -> Dict[str, dict]:
    """{executor_id(str): compact per-executor state} for the HEALTH
    reply and the live monitor — string keys because this rides msgpack
    on the rendezvous wire (the HEALTH ``data`` convention)."""
    now = time.monotonic()
    out: Dict[str, dict] = {}
    with self._cond:
      for eid, e in self.executors.items():
        vals = {}
        for name in self.TOP_METRICS:
          m = e["metrics"].get(name)
          if m is not None and "value" in m:
            vals[name] = m["value"]
        for name, m in e["metrics"].items():
          if name.startswith(self.TOP_METRIC_PREFIXES) \
              and m is not None and "value" in m:
            vals[name] = m["value"]
        out[str(eid)] = {
            "label": e["label"], "pid": e["pid"], "ships": e["ships"],
            "last_seen_age": now - e.get("last_seen", now),
            "clock": dict(e["clock"]), "drops": dict(e["drops"]),
            "metrics": vals,
        }
    return out

  def summary(self) -> dict:
    now = time.monotonic()
    with self._cond:
      return {
          "executors": {
              eid: {"ships": e["ships"], "label": e["label"],
                    "pid": e["pid"], "drops": dict(e["drops"]),
                    "clock": dict(e["clock"]),
                    "last_seen_age": now - e.get("last_seen", now)}
              for eid, e in self.executors.items()},
          "spans_buffered": len(self._spans),
          "spans_dropped": self.spans_dropped,
          "ingested": self.ingested,
          "rejected": self.rejected,
      }
