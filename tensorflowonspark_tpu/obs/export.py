"""Export plane: per-process JSONL logs, Prometheus text, Chrome traces.

Three consumers, three formats:

- **JSONL event logs** (``TOS_OBS_DIR``): every obs-enabled process
  appends its spans (plus a meta header, its final clock offset and a
  final metrics snapshot) to ``obs-<label><id>-<pid>.jsonl``. Crash-safe
  by construction: each line is self-contained, a truncated tail loses
  only the last line.
- **Prometheus text exposition** (:func:`prometheus_text`): the registry
  snapshot (or the driver sink's per-executor totals) rendered in the
  standard ``# TYPE`` format for scrape endpoints / file-based collection.
- **Chrome trace JSON** (:func:`chrome_trace`): the merged per-node spans
  as a ``traceEvents`` array loadable in Perfetto / chrome://tracing,
  one process track per JSONL log, timestamps driver-anchored via each
  process's estimated clock offset (``obs.spans.ClockOffset``).

``tools/obs_report.py`` is the CLI over :func:`merge_jsonl` +
:func:`chrome_trace`.
"""

import glob
import json
import os
import threading
import time
from typing import Dict, List, Optional

#: directory for per-process JSONL event logs; unset disables the offline
#: log plane (env registry: TOS008)
ENV_OBS_DIR = "TOS_OBS_DIR"


def log_dir() -> Optional[str]:
  return os.environ.get(ENV_OBS_DIR) or None


class ProcessLog(object):
  """Append-only JSONL log for one process (no-op when no dir is set).

  Files are opened per append batch (open/write/close under ``with``):
  the log must survive SIGKILL mid-run with everything already appended,
  and a held-open fd in a long-lived executor is a leak class (TOS006).
  """

  def __init__(self, directory: Optional[str] = None, label: str = "proc",
               executor_id: int = 0, clock=None):
    self.directory = directory if directory is not None else log_dir()
    self.label = label
    self.executor_id = int(executor_id)
    self.clock = clock
    self.path = None
    if self.directory:
      self.path = os.path.join(
          self.directory,
          "obs-%s%d-%d.jsonl" % (label, self.executor_id, os.getpid()))
    self._lock = threading.Lock()
    self._meta_written = False

  def _append(self, records: List[dict]) -> None:
    if self.path is None or not records:
      return
    with self._lock:
      lines = []
      if not self._meta_written:
        self._meta_written = True
        lines.append(json.dumps({
            "kind": "meta", "label": self.label,
            "executor_id": self.executor_id, "pid": os.getpid(),
            "t_wall": time.time(), "t_mono": time.monotonic()}))
      lines.extend(json.dumps(r) for r in records)
      try:
        os.makedirs(self.directory, exist_ok=True)
        with open(self.path, "a") as f:
          f.write("\n".join(lines) + "\n")
      except OSError:
        # an unwritable obs dir must not take down the process being
        # observed; the merged report will simply miss this log
        self.path = None

  def append_spans(self, spans: List[dict]) -> None:
    self._append([dict(rec, kind="span") for rec in spans])

  def append_alerts(self, alerts: List[dict]) -> None:
    """Structured detector alerts (``obs.anomaly``), appended as they
    fire so a post-mortem (``obs_report --alerts``) survives a driver
    crash — each line is self-contained like every other record here."""
    self._append([dict(rec, kind="alert") for rec in alerts])

  def close(self, metrics_snapshot: Optional[dict] = None) -> None:
    """Stamp the final clock offset + metrics snapshot (merge anchors on
    the LAST clock line — the best estimate the process ever had)."""
    tail: List[dict] = []
    if self.clock is not None:
      tail.append(dict(self.clock.snapshot(), kind="clock"))
    if metrics_snapshot is not None:
      tail.append({"kind": "metrics", "data": metrics_snapshot})
    if not tail and not self._meta_written:
      return   # nothing was ever logged; leave no empty file behind
    self._append(tail)


# -- merge + chrome trace -----------------------------------------------------


def find_logs(directory: str) -> List[str]:
  return sorted(glob.glob(os.path.join(directory, "obs-*.jsonl")))


def merge_jsonl(paths: List[str]) -> List[dict]:
  """Parse per-process logs into proc dicts:
  ``{"path", "meta", "spans", "alerts", "metrics", "clock"}`` (malformed
  lines are skipped and counted in ``"skipped"``)."""
  procs = []
  for path in paths:
    proc = {"path": path, "meta": {}, "spans": [], "alerts": [],
            "metrics": {}, "clock": {}, "skipped": 0}
    try:
      with open(path) as f:
        lines = f.read().splitlines()
    except OSError as e:
      # unreadable log: surfaced in the report (never raised — a partial
      # merge beats no merge), counted so the gap is visible
      proc["error"] = str(e)
      procs.append(proc)
      continue
    for line in lines:
      if not line.strip():
        continue
      try:
        rec = json.loads(line)
        kind = rec.get("kind")
      except (ValueError, AttributeError):
        proc["skipped"] += 1
        continue
      if kind == "meta":
        proc["meta"] = rec
      elif kind == "span":
        proc["spans"].append(rec)
      elif kind == "alert":
        proc["alerts"].append(rec)
      elif kind == "clock":
        proc["clock"] = rec   # last one wins: the final (best) estimate
      elif kind == "metrics":
        proc["metrics"] = rec.get("data") or {}
      else:
        proc["skipped"] += 1
    procs.append(proc)
  return procs


def anchored_window(proc: dict) -> Optional[tuple]:
  """(first_start, last_end) of a proc's spans on the DRIVER timeline."""
  offset = float(proc.get("clock", {}).get("offset") or 0.0)
  spans = proc.get("spans") or []
  if not spans:
    return None
  starts = [s["t0"] + offset for s in spans]
  ends = [s["t0"] + s.get("dur", 0.0) + offset for s in spans]
  return min(starts), max(ends)


def _flow_id(trace: str) -> int:
  """Stable positive int id for a hex trace id (chrome flow ``id``).
  13 hex chars = 52 bits: trace viewers parse JSON numbers into float64,
  so ids must stay inside the 2**53 exact-integer range or two distinct
  traces can collapse onto one arrow chain after rounding."""
  try:
    return int(str(trace)[:13], 16) or 1
  except ValueError:
    return abs(hash(trace)) % (1 << 52) or 1


def _flow_events(spans_by_trace: Dict[str, List[dict]]) -> List[dict]:
  """Chrome flow events binding each trace's spans into one arrow chain.

  For every trace with >= 2 spans, the time-ordered chain gets a flow
  start (``ph: "s"``) on the first span, a step (``"t"``) on each
  middle one and a finish (``"f", bp: "e"``) on the last — all sharing
  ``id = _flow_id(trace)``, which is what renders the CROSS-PROCESS
  arrows (fleet dispatch → replica prefill → decode → stream, including
  a failover hop: both replicas' spans carry the same trace).
  """
  out = []
  for trace, spans in spans_by_trace.items():
    if len(spans) < 2:
      continue
    spans.sort(key=lambda e: e["ts"])
    fid = _flow_id(trace)
    for i, ev in enumerate(spans):
      ph = "s" if i == 0 else ("f" if i == len(spans) - 1 else "t")
      flow = {"ph": ph, "id": fid, "name": "req", "cat": "trace",
              "pid": ev["pid"], "tid": ev["tid"],
              # bind INSIDE the span's duration (chrome rejects flow
              # points outside their enclosing slice)
              "ts": ev["ts"] + min(1.0, ev.get("dur", 0.0) / 2.0)}
      if ph == "f":
        flow["bp"] = "e"
      out.append(flow)
  return out


def chrome_trace(procs: List[dict]) -> dict:
  """Perfetto/chrome://tracing JSON from merged proc logs.

  One trace "process" per log (pid = the real pid, disambiguated on
  collision), timestamps anchored with each proc's clock offset so every
  track shares the driver's monotonic timeline. Spans carrying a
  request ``trace`` id additionally get FLOW events (``ph: s/t/f``)
  chaining them across tracks/processes — the request waterfall's
  arrows (``obs_report --request`` renders the same chain as a table).
  """
  events = []
  used_pids = set()
  spans_by_trace: Dict[str, List[dict]] = {}
  for proc in procs:
    meta = proc.get("meta") or {}
    pid = int(meta.get("pid") or 0)
    while pid in used_pids:
      pid += 1000000   # same-pid logs (a respawn reusing a pid) split
    used_pids.add(pid)
    label = "%s%s" % (meta.get("label", "proc"),
                      meta.get("executor_id", ""))
    offset = float(proc.get("clock", {}).get("offset") or 0.0)
    events.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                   "args": {"name": label}})
    tids: Dict[str, int] = {}
    for rec in proc.get("spans") or []:
      tname = rec.get("tid") or "main"
      tid = tids.get(tname)
      if tid is None:
        tid = tids[tname] = len(tids) + 1
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
      ts_us = (rec["t0"] + offset) * 1e6
      ev = {"name": rec.get("name", "?"), "pid": pid, "tid": tid,
            "ts": ts_us, "cat": rec.get("name", "?").split(".")[0]}
      if rec.get("ph") == "i":
        ev["ph"] = "i"
        ev["s"] = "t"
      else:
        ev["ph"] = "X"
        ev["dur"] = rec.get("dur", 0.0) * 1e6
      if rec.get("attrs"):
        ev["args"] = dict(rec["attrs"])
      trace = rec.get("trace")
      if trace is not None:
        # surfaced in args (clickable in Perfetto) AND collected for
        # the flow-arrow chain below; instants join args-only
        ev.setdefault("args", {})["trace"] = trace
        if ev["ph"] == "X":
          spans_by_trace.setdefault(str(trace), []).append(ev)
      events.append(ev)
    for rec in proc.get("alerts") or []:
      # detector alerts land as GLOBAL instants: on the trace they mark
      # the moment the driver called the run unhealthy, across all tracks
      events.append({"name": "alert:%s" % rec.get("alert", "?"),
                     "pid": pid, "tid": 0, "ph": "i", "s": "g",
                     "ts": (rec.get("t", 0.0) + offset) * 1e6,
                     "cat": "alert",
                     "args": {k: v for k, v in rec.items()
                              if k not in ("kind", "t")}})
  events.extend(_flow_events(spans_by_trace))
  return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- prometheus text ----------------------------------------------------------


def _prom_name(name: str) -> str:
  out = []
  for ch in name:
    out.append(ch if ch.isalnum() or ch == "_" else "_")
  base = "".join(out)
  return base if base.startswith("tos_") else "tos_" + base


def _prom_labels(labels: Optional[Dict[str, str]], extra: str = "") -> str:
  parts = ['%s="%s"' % (k, v) for k, v in sorted((labels or {}).items())]
  if extra:
    parts.append(extra)
  return "{%s}" % ",".join(parts) if parts else ""


def prometheus_text(snapshot: Dict[str, dict],
                    labels: Optional[Dict[str, str]] = None) -> str:
  """Render a registry snapshot in Prometheus text exposition format."""
  lines: List[str] = []
  for name in sorted(snapshot):
    m = snapshot[name]
    pname = _prom_name(name)
    kind = m.get("type")
    if kind in ("counter", "gauge"):
      lines.append("# TYPE %s %s" % (pname, kind))
      lines.append("%s%s %s" % (pname, _prom_labels(labels), m["value"]))
    elif kind == "histogram":
      lines.append("# TYPE %s histogram" % pname)
      cum = 0
      for bound, cnt in zip(m["bounds"], m["counts"]):
        cum += cnt
        lines.append("%s_bucket%s %d" % (
            pname, _prom_labels(labels, 'le="%g"' % bound), cum))
      cum += m["counts"][-1]
      lines.append("%s_bucket%s %d" % (
          pname, _prom_labels(labels, 'le="+Inf"'), cum))
      lines.append("%s_sum%s %s" % (pname, _prom_labels(labels), m["sum"]))
      lines.append("%s_count%s %d" % (pname, _prom_labels(labels),
                                      m["count"]))
    elif kind == "sketch":
      # quantile sketches (obs.quantiles) render as a Prometheus
      # SUMMARY: the canonical quantile set straight off the sketch
      from tensorflowonspark_tpu.obs import quantiles as _q
      sk = _q.QuantileSketch.from_dict(m.get("data") or {})
      lines.append("# TYPE %s summary" % pname)
      for q in (0.5, 0.9, 0.99):
        v = sk.quantile(q)
        if v is not None:
          lines.append("%s%s %g" % (
              pname, _prom_labels(labels, 'quantile="%g"' % q), v))
      lines.append("%s_count%s %d" % (pname, _prom_labels(labels),
                                      sk.count))
  return "\n".join(lines) + ("\n" if lines else "")
