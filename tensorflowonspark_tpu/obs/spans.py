"""Span/event tracing on the monotonic clock, with driver-anchored offsets.

Every process records spans against its OWN ``time.monotonic()`` — the
only clock that never steps backwards under NTP. To land per-executor
traces on one timeline, each executor estimates its offset to the
DRIVER's monotonic clock with an NTP-style exchange piggybacked on
control-plane round-trips (the rendezvous ``BEAT``/``OBS`` replies carry
the server's monotonic timestamp): for a request sent at local ``t0``
and answered at ``t1`` carrying server time ``ts``, the offset sample is
``ts - (t0 + t1) / 2`` with uncertainty ``(t1 - t0) / 2``. The estimator
keeps the minimum-RTT sample of a sliding window, so chaos-injected (or
load-induced) delays inflate individual samples without poisoning the
estimate — one clean round-trip wins.

The recorder is BOUNDED and never blocks (TOS001 by construction): a
full buffer drops the newest record and counts it. Observability must
never wedge the runtime it observes.
"""

import contextlib
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

#: span-buffer capacity per process (records held between shipper drains;
#: env registry: TOS008)
ENV_OBS_SPAN_BUFFER = "TOS_OBS_SPAN_BUFFER"

_DEFAULT_CAPACITY = 4096


def new_trace_id() -> str:
  """A fresh request-scoped trace id (16 hex chars, unique across
  processes). Minted once per logical request at the submit boundary
  (``ServingFleet.submit`` / ``ServingEngine.submit``) and stamped onto
  every span the request touches — including across a cross-replica
  failover hop, which is what keeps one request ONE trace."""
  return uuid.uuid4().hex[:16]


def _coerce(v):
  """msgpack/json-safe attribute values (numpy scalars -> builtins)."""
  if isinstance(v, (str, int, float, bool, type(None))):
    return v
  if hasattr(v, "item"):
    try:
      return v.item()
    except Exception:  # noqa: BLE001 - non-scalar array etc.
      return str(v)
  return str(v)


class ClockOffset(object):
  """Min-RTT estimate of (driver monotonic − local monotonic).

  ``update`` is fed by whichever control-plane client sees server
  timestamps (HeartbeatSender beats, ObsShipper ships). ``offset`` is
  the current best estimate (0.0 until the first sample — a driver-side
  recorder simply never updates); ``rtt`` is the uncertainty of that
  sample (error is bounded by ±rtt/2).

  The last ``window`` samples are kept; once the elected sample ages
  out of the window, the minimum-RTT sample OF THE WINDOW is re-elected
  — so a one-off artificially-good sample from a past epoch cannot pin
  the estimate forever (process migration, clock-affecting events), and
  a re-election can never adopt a lone delayed sample while better
  recent ones exist.
  """

  def __init__(self, window: int = 64):
    self.window = int(window)
    self._lock = threading.Lock()
    self.offset = 0.0
    self.rtt = float("inf")
    self.samples = 0
    self._recent: deque = deque(maxlen=max(1, self.window))
    self._since_best = 0

  def update(self, t0: float, server_time: float, t1: float) -> None:
    rtt = max(0.0, t1 - t0)
    sample = server_time - 0.5 * (t0 + t1)
    with self._lock:
      self.samples += 1
      self._since_best += 1
      self._recent.append((rtt, sample))
      if rtt <= self.rtt:
        self.offset = sample
        self.rtt = rtt
        self._since_best = 0
      elif self._since_best >= self.window:
        # the elected sample aged out: re-elect the best RECENT one
        self.rtt, self.offset = min(self._recent, key=lambda rs: rs[0])
        self._since_best = 0

  def snapshot(self) -> dict:
    with self._lock:
      rtt = self.rtt if self.rtt != float("inf") else None
      return {"offset": self.offset, "rtt": rtt, "samples": self.samples}


class SpanRecorder(object):
  """Bounded per-process buffer of finished spans / instant events.

  Records are plain dicts (msgpack/json-safe)::

      {"name": "feed.batch", "ph": "X", "t0": <monotonic>, "dur": <s>,
       "tid": <thread name>, "attrs": {...}}       # span
      {"name": "cluster.stop", "ph": "i", "t0": <monotonic>, ...}  # event

  Request-scoped records additionally carry a TOP-LEVEL ``"trace"`` key
  (the :func:`new_trace_id` minted at submit): the export plane keys
  flow events and the ``obs_report --request`` waterfall on it, so it is
  a record field, not an attr. ``span``/``record_span``/``event`` take
  it as the ``trace=`` kwarg.

  ``add`` never blocks: past ``capacity`` the record is dropped and
  ``dropped`` incremented (the drop counter ships with every OBS delta,
  so lost spans are visible, not silent).
  """

  def __init__(self, capacity: Optional[int] = None,
               clock: Optional[ClockOffset] = None):
    if capacity is None:
      capacity = int(os.environ.get(ENV_OBS_SPAN_BUFFER,
                                    str(_DEFAULT_CAPACITY)))
    self.capacity = max(1, capacity)
    self.clock = clock if clock is not None else ClockOffset()
    self._buf: deque = deque()
    self.dropped = 0
    self.recorded = 0

  # -- hot path --------------------------------------------------------------

  def add(self, record: dict) -> None:
    # len/append under the GIL: worst case a burst briefly overshoots the
    # cap by a few records — bounded either way, and never a lock wait
    if len(self._buf) >= self.capacity:
      self.dropped += 1
      return
    self.recorded += 1
    self._buf.append(record)

  @contextlib.contextmanager
  def span(self, name: str, trace: Optional[str] = None, **attrs):
    t0 = time.monotonic()
    try:
      yield
    finally:
      dur = time.monotonic() - t0
      rec = {"name": name, "ph": "X", "t0": t0, "dur": dur,
             "tid": threading.current_thread().name}
      if trace is not None:
        rec["trace"] = trace
      if attrs:
        rec["attrs"] = {k: _coerce(v) for k, v in attrs.items()}
      self.add(rec)

  def record_span(self, name: str, t0: float, dur: float,
                  trace: Optional[str] = None, **attrs) -> None:
    """Record a span from caller-measured timestamps (for seams that
    already hold a ``perf_counter``-free monotonic pair)."""
    rec = {"name": name, "ph": "X", "t0": t0, "dur": dur,
           "tid": threading.current_thread().name}
    if trace is not None:
      rec["trace"] = trace
    if attrs:
      rec["attrs"] = {k: _coerce(v) for k, v in attrs.items()}
    self.add(rec)

  def event(self, name: str, trace: Optional[str] = None, **attrs) -> None:
    rec = {"name": name, "ph": "i", "t0": time.monotonic(),
           "tid": threading.current_thread().name}
    if trace is not None:
      rec["trace"] = trace
    if attrs:
      rec["attrs"] = {k: _coerce(v) for k, v in attrs.items()}
    self.add(rec)

  # -- drain plane -----------------------------------------------------------

  def __len__(self) -> int:
    return len(self._buf)

  def drain(self, max_records: Optional[int] = None) -> List[dict]:
    """Pop up to ``max_records`` oldest records (all, when None)."""
    out: List[dict] = []
    n = len(self._buf) if max_records is None else max_records
    for _ in range(n):
      try:
        out.append(self._buf.popleft())
      except IndexError:
        break
    return out

  def drop_counts(self) -> Dict[str, int]:
    return {"spans_dropped": self.dropped, "spans_recorded": self.recorded}


# -- the process-active recorder ----------------------------------------------

_active: Optional[SpanRecorder] = None
_active_lock = threading.Lock()


def active() -> Optional[SpanRecorder]:
  """The process recorder, or None when the obs plane is off (mirrors
  ``metrics.active``)."""
  from tensorflowonspark_tpu.obs import metrics
  global _active
  if _active is None and metrics.enabled():
    with _active_lock:
      if _active is None:
        _active = SpanRecorder()
  return _active


def activate(recorder: Optional[SpanRecorder] = None) -> SpanRecorder:
  global _active
  with _active_lock:
    _active = recorder if recorder is not None else SpanRecorder()
    return _active


def deactivate() -> None:
  global _active
  with _active_lock:
    _active = None
