"""Cluster-wide observability plane.

One measurement story:

- ``metrics``   — process-local counters/gauges/fixed-bucket histograms
                  (lock-cheap hot path), snapshot/delta arithmetic, and
                  the live-stats snapshot-subtract helper the benches use
- ``spans``     — monotonic-clock span/event recording (bounded, never
                  blocking) + driver-anchored clock-offset estimation
                  piggybacked on rendezvous round-trips
- ``collector`` — executor-side delta shipper (the rendezvous ``OBS``
                  verb) and the driver-side ``ObsSink`` aggregation
- ``export``    — per-process JSONL event logs, Prometheus text
                  exposition, merged Chrome-trace (Perfetto) JSON
- ``profiler``  — JAX trace plumbing, ``StepTimer`` (feeds the registry)
                  and MFU accounting, moved from ``utils/profiler.py``
- ``device``    — compile/device tier: jax.monitoring recompile
                  sentinel (+ per-seam trace counters), HLO cost
                  capture, device-memory gauges on the shipper cadence
- ``anomaly``   — the driver-side DETECTOR loop consuming the sink
                  online: straggler / feed-stall / recompile-storm /
                  serving-saturation / memory-slope alerts, fanned out
                  to the registry, the supervisor event stream, the
                  driver JSONL and the rendezvous HEALTH verb

Everything is off (and near-free: one cached None check per seam) until
``TOS_OBS=1``. See docs/OBSERVABILITY.md for the metric catalogue, span
naming convention and overhead budget.

NOTE: only the dependency-free core (``metrics``, ``spans``) is imported
here — ``collector`` reaches into the rendezvous control plane, which
itself imports ``obs.spans``, so eager re-export would cycle.
"""

from tensorflowonspark_tpu.obs import metrics, spans  # noqa: F401
