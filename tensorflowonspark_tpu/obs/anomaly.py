"""Driver-side online anomaly & straggler detection over the ObsSink.

PR 7's plane records and ships telemetry; nothing consumed it online — a
recompile storm, a stalled feed stage, a straggling executor or a
device-memory creep was only visible after the run, in a Chrome trace a
human had to open. The :class:`AnomalyDetector` is the consumer: a
bounded, timeout-bounded driver thread that samples each executor's
cumulative totals from the :class:`obs.collector.ObsSink` on a fixed
cadence, keeps a rolling window per executor, and evaluates the detector
catalogue every pass:

==================  =========================================================
``straggler``       executor step rate below the cluster-median rate by more
                    than ``TOS_OBS_STRAGGLER_PCT`` percent (the tf.data /
                    TPU-concurrency papers' step-time-variance signal)
``feed_stall``      the consumer spent more than ``TOS_OBS_FEED_STALL_FRAC``
                    of the window blocked in the feed plane, with per-stage
                    attribution (fetch vs decode vs assemble — the tf.data
                    paper's input-bound diagnosis; under a ``data.datapipe``
                    graph the dominant GRAPH stage is named instead,
                    ``pipe:<stage>``, so the alert points at the starved
                    transform)
``recompile_storm`` ``xla.compiles`` still advancing after the executor's
                    ``TOS_OBS_COMPILE_WARMUP`` grace (a jit seam keying on
                    data-dependent shapes; obs.device is the source)
``serving_saturated`` request queue depth at/over ``TOS_OBS_QUEUE_SAT`` with
                    slot occupancy ~1: the engine is goodput-bound, admit
                    fewer or add slots
``serve_crash_loop`` ``serve.engine_restarts`` advanced by
                    ``TOS_OBS_CRASH_LOOP`` or more inside the window: the
                    serving engine is crash-replaying repeatedly — a poison
                    request slipped past detection, or the device/runtime
                    is genuinely failing (docs/ROBUSTNESS.md)
``kv_pages_exhausted`` ``serve.kv_pages_free`` pinned at 0 across the whole
                    window while the request queue is non-empty: the paged
                    KV pool is the admission bottleneck — raise
                    ``TOS_SERVE_NUM_PAGES``, shrink
                    ``TOS_SERVE_PREFIX_PAGES``, or shed load
                    (docs/PERFORMANCE.md §paged KV)
``fleet_degraded``  ``fleet.replicas_active`` + ``replicas_draining`` below
                    ``fleet.replicas_total``: one or more serving replicas
                    were EJECTED (terminal death or failed health probes;
                    a draining replica is a rolling swap, not lost
                    capacity) — failover replay keeps accepted requests
                    completing, but the fleet is running without
                    redundancy; restore capacity (docs/ROBUSTNESS.md
                    §Fleet)
``fleet_saturated`` the fleet-aggregate queue is at/over
                    ``TOS_OBS_QUEUE_SAT`` per active replica with mean
                    occupancy ~1 while at FULL replica strength: every
                    replica is goodput-bound — the scale-up signal (the
                    ``serving_saturated`` thresholds applied fleet-wide):
                    add a replica
``group_lost``      ``training.groups_active`` below ``training.groups_total``:
                    one or more elastic training groups were lost or evicted
                    (``parallel.groups``) — surviving groups keep stepping
                    with the sync denominator shrunk, but capacity is gone:
                    re-admit the group or commit the shrink
                    (docs/ROBUSTNESS.md §Elastic training)
``sync_lag``        ``training.sync_ms`` at/over ``TOS_OBS_SYNC_LAG_MS``: the
                    last cross-group sync round ran close to (or into) its
                    deadline — a slow, stalled or partitioned group is
                    dragging every boundary; find it before the miss limit
                    evicts it
``mem_slope``       ``device.bytes_in_use`` grew monotonically by more than
                    ``TOS_OBS_MEM_SLOPE_PCT`` percent across the window (a
                    leak-shaped creep toward OOM)
``slo_burn``        an ``obs.slo`` objective (availability / p-quantile
                    TTFT / e2e) is burning its error budget at/over
                    ``TOS_SLO_BURN`` on BOTH the fast (``TOS_OBS_WINDOW``)
                    and slow (``TOS_SLO_SLOW_MULT`` ×) windows — the
                    service-level verdict the canary phase reads; cluster
                    scope, so ``executor_id`` is −1
``canary_degraded`` a deploy canary is live (``deploy.state`` at
                    CANARY/VERIFY) and either ``deploy.parity_failures``
                    advanced inside the window (the candidate's greedy
                    output diverged from the reference decode — the
                    sharpest possible wrongness signal) or the
                    canary-vs-baseline median-TTFT ratio
                    (``deploy.canary_ttft_ratio``) is at/over
                    ``TOS_OBS_CANARY_RATIO``: the rollout in flight is
                    hurting; the controller's VERIFY gate will roll it
                    back, this alert is the online operator signal
                    (docs/ROBUSTNESS.md §Continuous deployment)
==================  =========================================================

Every alert is a plain msgpack/json-safe dict (see :func:`make_alert`)
and is fanned out four ways, none of which can block the detector:
counted into the driver registry (``obs.alerts``, ``obs.alerts.<kind>``),
mirrored into the ClusterSupervisor's event stream (``alert-<kind>`` —
alerts land next to recoveries in ``supervisor.events``), appended to
the driver's obs JSONL (crash-safe post-mortem for
``tools/obs_report.py --alerts``), and kept in a bounded ring the
rendezvous HEALTH verb serves to out-of-process monitors
(``tools/obs_top.py``).

Invariants (PR 7's contract): zero work when ``TOS_OBS=0`` (the cluster
never constructs a detector), every buffer bounded, every wait
timeout-bounded, detector failures counted (``eval_failures``) not
raised, and alerts are COUNTED, never raised — the detector diagnoses,
the supervisor (and the operator) decide.
"""

import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from tensorflowonspark_tpu.obs import metrics as metrics_mod
from tensorflowonspark_tpu.obs import slo as slo_mod
from tensorflowonspark_tpu.obs import spans as spans_mod

logger = logging.getLogger(__name__)

#: detector-loop master gate (default ON with ``TOS_OBS=1``; ``0`` keeps
#: the plane shipping without online evaluation) — env registry: TOS008
ENV_OBS_DETECT = "TOS_OBS_DETECT"
#: seconds between detector passes (TOS008)
ENV_OBS_DETECT_INTERVAL = "TOS_OBS_DETECT_INTERVAL"
#: rolling evaluation window in seconds (TOS008)
ENV_OBS_WINDOW = "TOS_OBS_WINDOW"
#: straggler threshold: percent below the cluster-median step rate (TOS008)
ENV_OBS_STRAGGLER_PCT = "TOS_OBS_STRAGGLER_PCT"
#: recompile storm FIRES AT/ABOVE this many compiles per window after
#: warmup (i.e. up to limit−1 are tolerated) — TOS008
ENV_OBS_RECOMPILE_LIMIT = "TOS_OBS_RECOMPILE_LIMIT"
#: seconds after an executor is first seen before compiles count (TOS008)
ENV_OBS_COMPILE_WARMUP = "TOS_OBS_COMPILE_WARMUP"
#: feed stall: fraction of the window spent inside feed stages (TOS008)
ENV_OBS_FEED_STALL_FRAC = "TOS_OBS_FEED_STALL_FRAC"
#: serving saturation: queue depth at/over this with occupancy ~1 (TOS008)
ENV_OBS_QUEUE_SAT = "TOS_OBS_QUEUE_SAT"
#: serve crash loop FIRES AT/ABOVE this many engine restarts per window
#: (TOS008)
ENV_OBS_CRASH_LOOP = "TOS_OBS_CRASH_LOOP"
#: memory slope: percent in-use growth across the window that fires (TOS008)
ENV_OBS_MEM_SLOPE_PCT = "TOS_OBS_MEM_SLOPE_PCT"
#: cross-group sync round latency (ms) at/over which ``sync_lag`` fires
#: (TOS008)
ENV_OBS_SYNC_LAG_MS = "TOS_OBS_SYNC_LAG_MS"
#: per-(kind, executor) refire suppression in seconds (TOS008)
ENV_OBS_ALERT_COOLDOWN = "TOS_OBS_ALERT_COOLDOWN"
#: canary degradation: canary/baseline median-TTFT ratio at/over which
#: ``canary_degraded`` fires while a deploy canary is live (TOS008)
ENV_OBS_CANARY_RATIO = "TOS_OBS_CANARY_RATIO"

_DEFAULT_INTERVAL = 2.0
_DEFAULT_WINDOW = 20.0
_DEFAULT_STRAGGLER_PCT = 50.0
_DEFAULT_RECOMPILE_LIMIT = 3
_DEFAULT_COMPILE_WARMUP = 120.0
_DEFAULT_FEED_STALL_FRAC = 0.6
_DEFAULT_QUEUE_SAT = 8
_DEFAULT_CRASH_LOOP = 2
_DEFAULT_MEM_SLOPE_PCT = 10.0
_DEFAULT_COOLDOWN = 30.0
_DEFAULT_SYNC_LAG_MS = 2000.0
_DEFAULT_CANARY_RATIO = 10.0

#: bounded alert ring (driver memory; the JSONL keeps the full history)
MAX_ALERTS = 256
#: a straggler verdict needs the median executor to have made at least
#: this many steps inside the window — below it, rates are noise
MIN_WINDOW_STEPS = 5
#: memory slope needs at least this many samples across the window
MIN_MEM_SAMPLES = 3

#: the datapipe executor's per-stage busy gauges: ``feed.stage.<name>.busy_s``
#: (dynamic stage names — sampled by prefix, not by the fixed list below)
_PIPE_PREFIX = "feed.stage."
_PIPE_SUFFIX = ".busy_s"

#: the cumulative/gauge metric names one detector pass reads per executor
_SAMPLED = ("train.steps", "train.unroll", "feed.batches", "feed.fetch_s",
            "feed.decode_s", "feed.assemble_s", "xla.compiles",
            "serve.queue_depth", "serve.occupancy",
            "serve.engine_restarts", "serve.replays",
            "serve.kv_pages_free", "serve.kv_pages_in_use",
            "fleet.replicas_total", "fleet.replicas_active",
            "fleet.replicas_draining", "fleet.queue_depth",
            "fleet.occupancy",
            "serve.hosts_total", "serve.hosts_alive",
            "training.groups_total", "training.groups_active",
            "training.sync_ms",
            "deploy.state", "deploy.version", "deploy.candidate",
            "deploy.canary_ttft_ratio", "deploy.parity_failures",
            "deploy.canaries", "deploy.promotions", "deploy.rollbacks",
            "device.bytes_in_use")


def detect_enabled() -> bool:
  """True when the obs plane is on and the detector loop isn't opted out."""
  return metrics_mod.enabled() and \
      os.environ.get(ENV_OBS_DETECT, "1") not in ("0",)


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


def make_alert(kind: str, executor_id: int, window_s: float,
               evidence: Dict, message: str, t: Optional[float] = None
               ) -> dict:
  """One structured alert record. ``alert`` (not ``kind``) carries the
  detector name so the record can ride the obs JSONL, whose per-line
  ``kind`` field is the record-type discriminator."""
  return {"alert": kind, "executor_id": int(executor_id),
          "t": time.monotonic() if t is None else t,
          "window_s": round(float(window_s), 3),
          "evidence": {k: (round(v, 4) if isinstance(v, float) else v)
                       for k, v in evidence.items()},
          "message": message}


class AnomalyDetector(object):
  """Rolling-window detector loop over a driver-side ObsSink.

  ``sink`` needs only ``metrics(eid) -> {name: snapshot}`` per executor
  and ``executors`` keys — tests drive synthetic sinks. ``supervisor``
  (optional) receives each alert via its ``_event`` stream;
  ``jsonl`` (optional, an ``obs.export.ProcessLog``) gets crash-safe
  per-alert appends. ``time_fn`` injects a clock for deterministic tests.
  """

  def __init__(self, sink, supervisor=None, jsonl=None,
               interval: Optional[float] = None,
               window: Optional[float] = None,
               registry=None, recorder=None, time_fn=time.monotonic,
               slo_tracker=None):
    self.sink = sink
    self.supervisor = supervisor
    self.jsonl = jsonl
    self.interval = max(0.05, interval if interval is not None else
                        _env_float(ENV_OBS_DETECT_INTERVAL,
                                   _DEFAULT_INTERVAL))
    self.window = max(2 * self.interval, window if window is not None else
                      _env_float(ENV_OBS_WINDOW, _DEFAULT_WINDOW))
    self.straggler_pct = _env_float(ENV_OBS_STRAGGLER_PCT,
                                    _DEFAULT_STRAGGLER_PCT)
    self.recompile_limit = _env_float(ENV_OBS_RECOMPILE_LIMIT,
                                      _DEFAULT_RECOMPILE_LIMIT)
    self.compile_warmup = _env_float(ENV_OBS_COMPILE_WARMUP,
                                     _DEFAULT_COMPILE_WARMUP)
    self.feed_stall_frac = _env_float(ENV_OBS_FEED_STALL_FRAC,
                                      _DEFAULT_FEED_STALL_FRAC)
    self.queue_sat = _env_float(ENV_OBS_QUEUE_SAT, _DEFAULT_QUEUE_SAT)
    self.crash_loop_limit = _env_float(ENV_OBS_CRASH_LOOP,
                                       _DEFAULT_CRASH_LOOP)
    self.mem_slope_pct = _env_float(ENV_OBS_MEM_SLOPE_PCT,
                                    _DEFAULT_MEM_SLOPE_PCT)
    self.sync_lag_ms = _env_float(ENV_OBS_SYNC_LAG_MS,
                                  _DEFAULT_SYNC_LAG_MS)
    self.canary_ratio = _env_float(ENV_OBS_CANARY_RATIO,
                                   _DEFAULT_CANARY_RATIO)
    self.cooldown = _env_float(ENV_OBS_ALERT_COOLDOWN, _DEFAULT_COOLDOWN)
    #: detectors only evaluate once a window's sample span reaches this —
    #: sub-second startup windows turn executor launch skew into phantom
    #: stragglers (seen in the bring-up drive: a 0.2 s window where one
    #: executor had stepped and the other hadn't yet)
    self.min_span = max(2 * self.interval, 0.5 * self.window)
    self._time = time_fn
    self._reg = registry if registry is not None else metrics_mod.active()
    self._rec = recorder if recorder is not None else spans_mod.active()
    #: the SLO plane (obs.slo): objectives declared via TOS_SLO_* ride
    #: this loop's cadence — sample + burn-rate evaluate per pass, with
    #: ``slo_burn`` fanned out exactly like every other alert. Falsy
    #: (no objectives) = the whole check is one truthiness test.
    self.slo = slo_tracker if slo_tracker is not None else \
        slo_mod.SLOTracker(window=self.window)
    # eid -> deque[(t, {name: float})]; capped well past window/interval
    self._samples: Dict[int, deque] = {}
    self._first_seen: Dict[int, float] = {}
    self._last_fired: Dict[tuple, float] = {}
    self._poll_lock = threading.Lock()
    self._cond = threading.Condition()
    self._alerts: deque = deque(maxlen=MAX_ALERTS)
    self.alerts_total = 0
    self.counts_by_kind: Dict[str, int] = {}
    self.eval_failures = 0
    # last pass's full per-executor metric snapshots (set by _sample)
    self._pass_metrics: Dict[int, Dict] = {}
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None

  # -- sampling --------------------------------------------------------------

  @staticmethod
  def _extract(metrics_snapshot: Dict[str, dict]) -> Dict[str, float]:
    vals = {}
    for name in _SAMPLED:
      m = metrics_snapshot.get(name)
      if m is not None and "value" in m:
        vals[name] = float(m["value"])
    # the datapipe executor's per-stage busy gauges (dynamic names —
    # one per declared graph stage) feed the feed_stall detector's
    # per-graph-stage attribution
    for name, m in metrics_snapshot.items():
      if name.startswith(_PIPE_PREFIX) and name.endswith(_PIPE_SUFFIX) \
          and m is not None and "value" in m:
        vals[name] = float(m["value"])
    return vals

  def _sample(self, now: float) -> None:
    # full per-executor snapshots for THIS pass: the scalar extract
    # below feeds the component detectors, while the SLO check needs
    # the raw state (quantile sketches aren't scalars) — one fetch
    # serves both
    self._pass_metrics = {}
    for eid in list(getattr(self.sink, "executors", {})):
      try:
        snap = self.sink.metrics(eid)
        vals = self._extract(snap)
      except Exception:  # noqa: BLE001 - a sink hiccup skips one sample
        self.eval_failures += 1
        continue
      self._pass_metrics[int(eid)] = snap
      dq = self._samples.setdefault(int(eid), deque(maxlen=4096))
      self._first_seen.setdefault(int(eid), now)
      dq.append((now, vals))
      # retire samples older than the window, always keeping the newest
      # pre-window sample as the delta baseline
      while len(dq) >= 2 and dq[1][0] <= now - self.window:
        dq.popleft()

  @staticmethod
  def _delta(dq, name: str) -> Optional[float]:
    first, last = dq[0][1].get(name), dq[-1][1].get(name)
    if first is None or last is None:
      return None
    return last - first

  # -- evaluation ------------------------------------------------------------

  def poll(self, now: Optional[float] = None) -> List[dict]:
    """One sample + evaluate pass; returns the alerts fired by THIS pass
    (they are also recorded/fanned out). The thread loop calls this; so
    do tests, with an injected ``now``."""
    if now is None:
      now = self._time()
    new: List[dict] = []
    # one evaluator at a time: a caller-driven poll (tests, the shutdown
    # final pass) must not interleave with the loop thread's — the
    # cooldown's check-then-set isn't atomic on its own
    with self._poll_lock:
      new.extend(self._poll_locked(now))
    return new

  def _poll_locked(self, now: float) -> List[dict]:
    new: List[dict] = []
    try:
      self._sample(now)
      windows = {}
      for eid, dq in self._samples.items():
        span = dq[-1][0] - dq[0][0]
        if len(dq) >= 2 and span >= self.min_span:
          windows[eid] = (dq, span)
      new.extend(self._check_stragglers(windows, now))
      for eid, (dq, span) in windows.items():
        new.extend(self._check_feed_stall(eid, dq, span, now))
        new.extend(self._check_recompiles(eid, dq, span, now))
        new.extend(self._check_serving(eid, dq, span, now))
        new.extend(self._check_serve_crash_loop(eid, dq, span, now))
        new.extend(self._check_kv_pages(eid, dq, span, now))
        new.extend(self._check_fleet(eid, dq, span, now))
        new.extend(self._check_hosts(eid, dq, span, now))
        new.extend(self._check_groups(eid, dq, span, now))
        new.extend(self._check_deploy(eid, dq, span, now))
        new.extend(self._check_mem_slope(eid, dq, span, now))
      new.extend(self._check_slo(now))
    except Exception:  # noqa: BLE001 - the detector must outlive any
      # single evaluation bug; failures are counted and visible
      self.eval_failures += 1
      logger.exception("anomaly evaluation pass failed")
    return new

  def _check_stragglers(self, windows, now) -> List[dict]:
    rates = {}
    for eid, (dq, span) in windows.items():
      d = self._delta(dq, "train.steps")
      if d is not None:
        rates[eid] = d / span
    # a lone executor has no cluster to straggle behind
    if len(rates) < 2:
      return []
    ordered = sorted(rates.values())
    median = ordered[len(ordered) // 2]
    span = max(s for _, s in windows.values())
    if median * span < MIN_WINDOW_STEPS:
      return []   # the cluster itself is barely stepping: rates are noise
    out = []
    threshold = median * (1.0 - self.straggler_pct / 100.0)
    for eid, rate in rates.items():
      if rate >= threshold:
        continue
      # fused-loop burst quantization (make_train_loop): steps arrive K
      # at a time, so an executor whose slab dispatch straddles the
      # window edge can show up to one slab (train.unroll) fewer steps
      # than its healthy peers — being behind by AT MOST one burst is
      # sampling noise, not straggling
      dq, span = windows[eid]
      burst = max(1.0, dq[-1][1].get("train.unroll", 1.0))
      behind_steps = (median - rate) * span
      if behind_steps <= burst:
        continue
      out.extend(self._fire(
          "straggler", eid, span, now,
          {"rate": rate, "cluster_median": median,
           "pct_behind": 100.0 * (1.0 - rate / median) if median else 0.0},
          "executor %d steps at %.2f/s vs cluster median %.2f/s "
          "(>%g%% behind)" % (eid, rate, median, self.straggler_pct)))
    return out

  def _check_feed_stall(self, eid, dq, span, now) -> List[dict]:
    stages = {s: self._delta(dq, "feed.%s" % s) or 0.0
              for s in ("fetch_s", "decode_s", "assemble_s")}
    # per-graph-stage attribution: a datapipe executor exports one
    # ``feed.stage.<name>.busy_s`` per declared stage (the classic
    # three stay zero in graph mode and vice versa, so the union never
    # double-counts). The alert then NAMES the starved transform
    # (``pipe:map0``), not just "fetch".
    for name in dq[-1][1]:
      if name.startswith(_PIPE_PREFIX) and name.endswith(_PIPE_SUFFIX):
        short = name[len(_PIPE_PREFIX):-len(_PIPE_SUFFIX)]
        stages["pipe:" + short] = self._delta(dq, name) or 0.0
    total = sum(stages.values())
    batches = self._delta(dq, "feed.batches")
    if batches is None:   # no DataFeed on this executor (FILES mode)
      return []
    if dq[-1][1].get("feed.batches", 0.0) <= 0:
      return []   # never delivered anything: bring-up, not a stall
    if batches > 0:
      return []   # fresh batches landed: the feed kept up. (The fetch
      # PIPELINE thread accrues fetch_s even while batches flow — stage
      # seconds alone cannot distinguish healthy overlap from a stall.)
    steps = self._delta(dq, "train.steps")
    if steps is not None and steps > 0:
      return []   # consumer progressed on buffered data: not starved yet
    if total < self.feed_stall_frac * span:
      return []
    stage = max(stages, key=stages.get)
    return self._fire(
        "feed_stall", eid, span, now,
        dict(stages, batches=batches, frac=total / span, stage=stage),
        "executor %d starved: zero fresh batches over %.0fs while the "
        "feed plane ran %.0f%% of it (dominant stage: %s) — input-bound "
        "or upstream stopped feeding" % (eid, span, 100 * total / span,
                                         stage))

  def _check_recompiles(self, eid, dq, span, now) -> List[dict]:
    if now - self._first_seen.get(eid, now) < self.compile_warmup:
      return []
    d = self._delta(dq, "xla.compiles")
    if d is None or d < self.recompile_limit:
      return []
    return self._fire(
        "recompile_storm", eid, span, now,
        {"compiles": d, "total": dq[-1][1].get("xla.compiles", 0.0)},
        "executor %d compiled %d time(s) in the last %.0fs, past its "
        "%.0fs warmup — a jit seam is keying on data-dependent shapes"
        % (eid, int(d), span, self.compile_warmup))

  def _check_serving(self, eid, dq, span, now) -> List[dict]:
    depth = dq[-1][1].get("serve.queue_depth")
    occ = dq[-1][1].get("serve.occupancy")
    if depth is None or occ is None:
      return []
    if depth < self.queue_sat or occ < 0.9:
      return []
    return self._fire(
        "serving_saturated", eid, span, now,
        {"queue_depth": depth, "occupancy": occ},
        "executor %d serving at occupancy %.2f with %d queued request(s) "
        "— goodput-bound; add slots or shed load" % (eid, occ, int(depth)))

  def _check_serve_crash_loop(self, eid, dq, span, now) -> List[dict]:
    d = self._delta(dq, "serve.engine_restarts")
    if d is None or d < self.crash_loop_limit:
      return []
    replays = self._delta(dq, "serve.replays") or 0.0
    return self._fire(
        "serve_crash_loop", eid, span, now,
        {"restarts": d, "replays": replays,
         "total_restarts": dq[-1][1].get("serve.engine_restarts", 0.0)},
        "executor %d serving engine restarted %d time(s) in the last "
        "%.0fs (%d request replays) — crash-looping: a poison request "
        "slipped past detection, or the device/runtime is failing"
        % (eid, int(d), span, int(replays)))

  def _check_kv_pages(self, eid, dq, span, now) -> List[dict]:
    """Paged-KV pool exhaustion: free pages PINNED at zero for the whole
    window (a transient dip to 0 between completions is normal — any
    sample above 0 clears the verdict) while requests are queued waiting
    for pages. The fix is capacity-shaped, not load-shaped, so this is
    its own kind rather than a ``serving_saturated`` variant."""
    frees = [v["serve.kv_pages_free"] for _, v in dq
             if "serve.kv_pages_free" in v]
    if len(frees) < 2:
      return []   # paging off, or not enough window to call it pinned
    if max(frees) > 0:
      return []
    depth = dq[-1][1].get("serve.queue_depth")
    if depth is None or depth <= 0:
      return []   # nothing waiting: a full pool at zero queue is just full
    in_use = dq[-1][1].get("serve.kv_pages_in_use", 0.0)
    return self._fire(
        "kv_pages_exhausted", eid, span, now,
        {"queue_depth": depth, "pages_in_use": in_use,
         "samples_at_zero": len(frees)},
        "executor %d KV page pool pinned at 0 free pages for %.0fs with "
        "%d queued request(s) — paging is the admission bottleneck: "
        "raise TOS_SERVE_NUM_PAGES, shrink TOS_SERVE_PREFIX_PAGES, or "
        "shed load" % (eid, span, int(depth)))

  def _check_fleet(self, eid, dq, span, now) -> List[dict]:
    """The serving-fleet pair: ``fleet_degraded`` when the router runs
    below its configured replica count (ejection visible online, not
    just in the event log), and ``fleet_saturated`` — the SCALE-UP
    signal — when the fleet is at full strength yet every replica is
    goodput-bound (the ``serving_saturated`` thresholds applied to the
    fleet aggregate: queue ≥ ``TOS_OBS_QUEUE_SAT`` per active replica at
    mean occupancy ~1). Degraded and saturated are different verdicts on
    purpose: the first says restore capacity, the second says add it."""
    latest = dq[-1][1]
    total = latest.get("fleet.replicas_total")
    active = latest.get("fleet.replicas_active")
    if total is None or active is None or total <= 0:
      return []
    # a DRAINING replica is a rolling swap in progress — healthy,
    # operator-initiated, zero-shed — not lost capacity: alarming on it
    # would train operators to ignore the real ejection signal
    draining = latest.get("fleet.replicas_draining") or 0.0
    if active + draining < total:
      return self._fire(
          "fleet_degraded", eid, span, now,
          {"replicas_active": active, "replicas_draining": draining,
           "replicas_total": total},
          "serving fleet on executor %d running %d/%d replicas — "
          "ejected replica(s) failed over; accepted requests keep "
          "completing but redundancy is gone: restore capacity"
          % (eid, int(active), int(total)))
    if active < total:
      return []   # mid-swap: saturation readings are perturbed anyway
    depth = latest.get("fleet.queue_depth")
    occ = latest.get("fleet.occupancy")
    if depth is None or occ is None:
      return []
    if depth < self.queue_sat * max(1.0, active) or occ < 0.9:
      return []
    return self._fire(
        "fleet_saturated", eid, span, now,
        {"queue_depth": depth, "occupancy": occ,
         "replicas_active": active},
        "serving fleet on executor %d saturated at full strength: %d "
        "queued request(s) across %d replicas at occupancy %.2f — "
        "scale up: add a replica" % (eid, int(depth), int(active), occ))

  def _check_hosts(self, eid, dq, span, now) -> List[dict]:
    """``host_lost``: the cross-host serving plane is syncing fewer
    ServingHosts than it has registered — a host process died, was
    preempted, or is partitioned past ``TOS_HOST_TIMEOUT``. Distinct
    from ``fleet_saturated`` on purpose: saturation fires only at FULL
    strength (every replica alive, goodput-bound — the scale-up
    signal); a lost host is missing capacity regardless of load (the
    restore-capacity signal), so this keys purely on the alive/total
    gap and carries the fleet's load gauges as evidence to make the
    distinction legible in the alert itself."""
    latest = dq[-1][1]
    total = latest.get("serve.hosts_total")
    alive = latest.get("serve.hosts_alive")
    if total is None or alive is None or total <= 0 or alive >= total:
      return []
    return self._fire(
        "host_lost", eid, span, now,
        {"hosts_alive": alive, "hosts_total": total,
         "fleet_queue_depth": latest.get("fleet.queue_depth") or 0.0,
         "fleet_occupancy": latest.get("fleet.occupancy") or 0.0},
        "cross-host serving plane on executor %d syncing %d/%d host(s) "
        "— a ServingHost died or is partitioned; its replica is being "
        "ejected and its accepted requests failover-replayed: restore "
        "the host (this is lost capacity, not saturation)"
        % (eid, int(alive), int(total)))

  def _check_groups(self, eid, dq, span, now) -> List[dict]:
    """The elastic-training pair (``parallel.groups``): ``group_lost``
    when the group set runs below its total — a group died or was
    evicted, surviving groups keep stepping with the sync denominator
    shrunk, but the lost throughput stays lost until someone re-admits
    the group or commits the shrink — and ``sync_lag`` when the last
    cross-group sync round took at/over ``TOS_OBS_SYNC_LAG_MS``: a
    slow or stalled group is dragging every boundary toward the round
    deadline, and past the miss limit the plane will evict it."""
    latest = dq[-1][1]
    out: List[dict] = []
    total = latest.get("training.groups_total")
    active = latest.get("training.groups_active")
    if total is not None and active is not None and total > 0 \
        and active < total:
      out.extend(self._fire(
          "group_lost", eid, span, now,
          {"groups_active": active, "groups_total": total},
          "elastic training on executor %d running %d/%d groups — "
          "lost group(s) shrank the sync denominator; training "
          "continues degraded: re-admit the group or commit the "
          "shrink" % (eid, int(active), int(total))))
    sync_ms = latest.get("training.sync_ms")
    if sync_ms is not None and sync_ms >= self.sync_lag_ms:
      out.extend(self._fire(
          "sync_lag", eid, span, now,
          {"sync_ms": sync_ms, "threshold_ms": self.sync_lag_ms},
          "cross-group weight sync on executor %d took %.0fms "
          "(threshold %.0fms) — a slow or stalled group is dragging "
          "rounds toward the deadline"
          % (eid, sync_ms, self.sync_lag_ms)))
    return out

  def _check_deploy(self, eid, dq, span, now) -> List[dict]:
    """``canary_degraded``: a rollout canary is live (``deploy.state``
    at CANARY/VERIFY) and hurting — parity spot-checks diverged inside
    the window, or the canary-vs-baseline median-TTFT ratio is at/over
    ``TOS_OBS_CANARY_RATIO``. The controller's own VERIFY gate decides
    the rollback; this is the ONLINE operator signal (and the one the
    bake-window check reads back through ``slo_status``-style plumbing),
    so it keys on the candidate version: a second candidate gets its own
    cooldown."""
    latest = dq[-1][1]
    state = latest.get("deploy.state")
    if state is None or int(state) not in (1, 2):   # CANARY, VERIFY
      return []
    candidate = int(latest.get("deploy.candidate") or 0)
    out: List[dict] = []
    parity = self._delta(dq, "deploy.parity_failures")
    ratio = latest.get("deploy.canary_ttft_ratio")
    if parity is not None and parity > 0:
      out.extend(self._fire(
          "canary_degraded", eid, span, now,
          {"candidate": candidate, "parity_failures": parity},
          "deploy canary for version %d diverged from the reference "
          "decode %d time(s) in the window — the candidate is serving "
          "wrong outputs; VERIFY will quarantine it"
          % (candidate, int(parity)),
          key=("canary_degraded", "parity", candidate)))
    if ratio is not None and ratio >= self.canary_ratio:
      out.extend(self._fire(
          "canary_degraded", eid, span, now,
          {"candidate": candidate, "ttft_ratio": ratio,
           "threshold": self.canary_ratio},
          "deploy canary for version %d running %.1fx baseline median "
          "TTFT (threshold %.1fx) — the candidate is slow; expect a "
          "rollback" % (candidate, ratio, self.canary_ratio),
          key=("canary_degraded", "ttft", candidate)))
    return out

  def deploy_status(self) -> Optional[dict]:
    """The HEALTH-wire deploy payload (None until some process ships
    ``deploy.*`` gauges): the newest sampled controller state, so
    ``obs_top`` can render the ``deploy[...]`` row without reaching the
    controller process. Read-side only — the authoritative state machine
    lives in ``serving.deploy``."""
    best = None
    best_t = None
    for dq in self._samples.values():
      if not dq:
        continue
      t, vals = dq[-1]
      if "deploy.state" not in vals:
        continue
      if best_t is None or t > best_t:
        best_t, best = t, vals
    if best is None:
      return None
    names = ("idle", "canary", "verify", "promote", "rollback")
    code = int(best.get("deploy.state") or 0)
    return {"state": names[code] if 0 <= code < len(names) else str(code),
            "state_code": code,
            "version": int(best.get("deploy.version") or 0) or None,
            "candidate": int(best.get("deploy.candidate") or 0) or None,
            "ttft_ratio": best.get("deploy.canary_ttft_ratio"),
            "canaries": int(best.get("deploy.canaries") or 0),
            "promotions": int(best.get("deploy.promotions") or 0),
            "rollbacks": int(best.get("deploy.rollbacks") or 0),
            "parity_failures": int(best.get("deploy.parity_failures")
                                   or 0)}

  def _check_slo(self, now) -> List[dict]:
    """Sample + burn-rate-evaluate the declared SLO objectives
    (``obs.slo``). Latency objectives read the cluster-MERGED quantile
    sketches straight off the sink's per-executor state (not the
    ``_SAMPLED`` float path — sketches aren't scalars), availability the
    summed serve counters; ``slo_burn`` fires per objective (its own
    cooldown key) at cluster scope, executor_id −1."""
    if not self.slo:
      return []
    self.slo.sample(now, self._pass_metrics)
    out = []
    for v in self.slo.evaluate(now):
      if not v.get("burning"):
        continue
      if v["kind"] == "latency":
        detail = ("%s=%.1fms over the %.0fms bound"
                  % (v["name"], v["observed"] or 0.0, v["threshold_ms"]))
      else:
        detail = "availability %.5f vs target %.5f" % (
            v["observed"] if v["observed"] is not None else 1.0,
            v["target"])
      out.extend(self._fire(
          "slo_burn", -1, v["window_slow"], now,
          {"objective": v["name"], "burn_fast": v["burn_fast"],
           "burn_slow": v["burn_slow"],
           "bad_frac_fast": v["bad_frac_fast"],
           "bad_frac_slow": v["bad_frac_slow"],
           "events_slow": v["events_slow"],
           "budget": v["budget"], "observed": v["observed"]},
          "SLO %s burning its error budget at %.1fx (fast) / %.1fx "
          "(slow) over the %.0fs/%.0fs windows — %s" % (
              v["name"], v["burn_fast"], v["burn_slow"],
              v["window_fast"], v["window_slow"], detail),
          key=("slo_burn", v["name"])))
    return out

  def slo_status(self) -> Optional[dict]:
    """The HEALTH-wire SLO payload (None when no objectives are
    declared) — ``Server`` attaches it to HEALTH replies next to the
    alert ring, and ``obs_top`` renders the ``slo[...]`` row off it."""
    if not self.slo:
      return None
    return self.slo.status(self._time())

  def _check_mem_slope(self, eid, dq, span, now) -> List[dict]:
    series = [(t, v["device.bytes_in_use"]) for t, v in dq
              if "device.bytes_in_use" in v]
    if len(series) < MIN_MEM_SAMPLES:
      return []
    values = [v for _, v in series]
    first, last = values[0], values[-1]
    if first <= 0 or last <= first or last < max(values):
      return []   # flat, shrinking, or already peaked — not a creep
    growth_pct = 100.0 * (last - first) / first
    if growth_pct < self.mem_slope_pct:
      return []
    return self._fire(
        "mem_slope", eid, span, now,
        {"first_bytes": first, "last_bytes": last,
         "growth_pct": growth_pct,
         "slope_bytes_per_s": (last - first) / span},
        "executor %d device memory grew %.1f%% over %.0fs (%.0f B/s) — "
        "leak-shaped creep" % (eid, growth_pct, span,
                               (last - first) / span))

  # -- alert fan-out ---------------------------------------------------------

  def _fire(self, kind, eid, span, now, evidence, message,
            key=None) -> List[dict]:
    # default cooldown key is (kind, executor); cluster-scope detectors
    # (slo_burn) pass their own so two objectives don't share a cooldown
    key = key if key is not None else (kind, int(eid))
    last = self._last_fired.get(key)
    if last is not None and now - last < self.cooldown:
      return []
    self._last_fired[key] = now
    alert = make_alert(kind, eid, span, evidence, message, t=now)
    logger.warning("obs alert: %s", message)
    with self._cond:
      self._alerts.append(alert)
      self.alerts_total += 1
      self.counts_by_kind[kind] = self.counts_by_kind.get(kind, 0) + 1
      self._cond.notify_all()
    if self._reg is not None:
      self._reg.counter("obs.alerts").inc()
      self._reg.counter("obs.alerts." + kind).inc()
    if self._rec is not None:
      self._rec.event("obs.alert", alert=kind, executor_id=int(eid))
    if self.supervisor is not None:
      try:
        # same stream as detected-dead/relaunched/recovered: the alert
        # IS a cluster event, and tests/operators already read this list
        self.supervisor._event("alert-" + kind, executor_id=int(eid),
                               message=message)
      except Exception:  # noqa: BLE001 - a supervisor in teardown must
        self.eval_failures += 1   # not take the detector with it
    if self.jsonl is not None:
      self.jsonl.append_alerts([alert])
    return [alert]

  # -- read plane ------------------------------------------------------------

  def recent_alerts(self, max_items: int = 64) -> List[dict]:
    """Newest-first bounded slice for HEALTH replies / obs_top."""
    with self._cond:
      items = list(self._alerts)[-max_items:]
    return list(reversed(items))

  def wait_alert(self, timeout: float, kind: Optional[str] = None
                 ) -> Optional[dict]:
    """Block (bounded) until an alert exists — newest matching one, or
    None on timeout. Named into the analyzer's blocking-verb set
    (TOS001): callers must pass an explicit ``timeout``."""
    deadline = time.monotonic() + timeout
    with self._cond:
      while True:
        for a in reversed(self._alerts):
          if kind is None or a["alert"] == kind:
            return dict(a)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          return None
        self._cond.wait(timeout=min(remaining, 0.25))

  def summary(self) -> dict:
    with self._cond:
      return {"alerts_total": self.alerts_total,
              "by_kind": dict(self.counts_by_kind),
              "eval_failures": self.eval_failures,
              "interval": self.interval, "window": self.window}

  # -- lifecycle -------------------------------------------------------------

  def _run(self) -> None:
    while not self._stop.wait(self.interval):
      self.poll()

  def start(self) -> "AnomalyDetector":
    self._thread = threading.Thread(target=self._run, daemon=True,
                                    name="tos-obs-anomaly")
    self._thread.start()
    return self

  def stop(self, timeout: float = 5.0) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=timeout)
      self._thread = None
