"""Pallas TPU kernels for hot ops.

The reference had no custom kernels (all math delegated to TF); on TPU the
few ops XLA cannot fuse optimally are written in Pallas:

- ``flash_attention`` — fused blockwise attention (softmax never
  materializes the full score matrix in HBM); the intra-block engine under
  ring attention's sequence parallelism.
"""

#: env registry (tools.analyze TOS008): "0"/"1" force real/interpret
#: Pallas execution; unset/"auto" = interpret off-TPU, real kernels on TPU
ENV_PALLAS_INTERPRET = "TOS_PALLAS_INTERPRET"


def pallas_interpret() -> bool:
  """Whether Pallas kernels should run in interpret (emulation) mode.

  Default policy: interpret off-TPU (how CPU CI trains through the
  production kernel paths), real Mosaic lowering on TPU. Override with
  ``TOS_PALLAS_INTERPRET=0`` to force real kernels even when the default
  backend is not TPU — that is how the deviceless Mosaic gate
  (tools/mosaic_gate.py) AOT-compiles every production kernel against a
  TPU topology from a CPU-only host, with no chip claimed. ``=1`` forces
  interpret everywhere (debugging on-chip numerics).
  """
  import os
  v = os.environ.get(ENV_PALLAS_INTERPRET, "auto").lower()
  if v in ("0", "false"):
    return False
  if v in ("1", "true"):
    return True
  import jax
  return jax.default_backend() != "tpu"


def pallas_kernels_enabled() -> bool:
  """Whether "auto" impl settings should pick the Pallas kernels at all.

  Distinct from :func:`pallas_interpret` (HOW kernels run) — this decides
  WHETHER "auto" uses them: on the real TPU backend, or under
  ``TOS_PALLAS_INTERPRET=0`` (the deviceless gate compiling FOR a TPU
  topology from a CPU client). ``TOS_PALLAS_INTERPRET=1`` on a TPU does
  NOT disable them — the kernels stay selected and run in interpret mode,
  which is the flag's on-chip numerics-debugging purpose.
  """
  import os
  if os.environ.get(ENV_PALLAS_INTERPRET, "").lower() in ("0", "false"):
    return True
  import jax
  return jax.default_backend() == "tpu"


from tensorflowonspark_tpu.ops.flash_attention import (  # noqa: F401,E402
    flash_attention, flash_attention_block, merge_partials,
)
from tensorflowonspark_tpu.ops.layer_norm import (  # noqa: F401
    layer_norm, layer_norm_sharded,
)
from tensorflowonspark_tpu.ops.act_matmul import (  # noqa: F401
    gelu_matmul, gelu_matmul_sharded,
)
from tensorflowonspark_tpu.ops.ln_matmul import (  # noqa: F401
    ln_matmul, ln_matmul_sharded,
)
