"""Pallas TPU kernels for hot ops.

The reference had no custom kernels (all math delegated to TF); on TPU the
few ops XLA cannot fuse optimally are written in Pallas:

- ``flash_attention`` — fused blockwise attention (softmax never
  materializes the full score matrix in HBM); the intra-block engine under
  ring attention's sequence parallelism.
"""

from tensorflowonspark_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention, flash_attention_block, merge_partials,
)
from tensorflowonspark_tpu.ops.layer_norm import (  # noqa: F401
    layer_norm, layer_norm_sharded,
)
from tensorflowonspark_tpu.ops.act_matmul import (  # noqa: F401
    gelu_matmul, gelu_matmul_sharded,
)
from tensorflowonspark_tpu.ops.ln_matmul import (  # noqa: F401
    ln_matmul, ln_matmul_sharded,
)
