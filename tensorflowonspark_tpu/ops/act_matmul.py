"""Fused GELU + matmul as a Pallas TPU kernel: ``gelu(x) @ W``.

The MLP down-projection twin of :mod:`ops.ln_matmul` (round-2 verdict
item 7's MFU hunt; the round-3 verdict named the "MLP down-proj pair" a
candidate for the next fusion): in every Transformer MLP the down-proj
matmul consumes a GELU output, and XLA materializes that activation in
HBM between the two HLOs. At d_ff = 4·d_model the [rows, d_ff] GELU
activation is the WIDEST tensor in the block — four times the LN
round-trip ln_matmul eliminates — so this kernel computes GELU on the
VPU and feeds the activated block straight into the MXU dot from VMEM.

Forward layout: x [..., F] (pre-activation, leading dims flatten to
rows), W [F, N]. Grid tiles (rows, N); each (i, j) step re-applies GELU
to its x block — one extra VPU pass per N-tile, cheaper than an HBM
round-trip of the [rows, F] activated tensor.

Backward: a custom VJP recomputes GELU and its derivative in plain XLA
(the backward is matmul-bound; the fusion win is the forward). GELU is
the tanh approximation, matching ``flax.linen.gelu``'s default so the
fused and unfused model paths are numerically interchangeable.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tensorflowonspark_tpu.ops.layer_norm import _pick_block
from tensorflowonspark_tpu.ops.ln_matmul import _pick_col_block


def _gelu_f32(x):
  # tanh-approximate GELU in f32 (flax nn.gelu default approximate=True)
  return jax.nn.gelu(x, approximate=True)


def effective_blocks(rows: int, f: int, n: int, blk_rows: int,
                     blk_cols: int, w_itemsize: int = 2):
  """The (row, col) block pair the kernel will ACTUALLY run.

  Here the CONTRACTED dim F = d_ff is the LARGE one (unlike ln_matmul,
  which contracts d_model), so both tiles carry byte-footprint caps or
  big-F f32 shapes blow VMEM at the default block sizes (the failure
  mode layer_norm._pick_block records): the x block keeps a f32
  activation copy (itemsize=4 cap) and the [F, blk_n] W tile is held to
  ~4 MiB with a 128-lane floor. Shared with tools/tpu_validate's block
  sweep so its dedup/labels track these caps exactly.
  """
  blk_r = _pick_block(rows, blk_rows, f, itemsize=4)
  cap = max(128, (4 << 20) // (f * w_itemsize))
  return blk_r, _pick_col_block(n, min(blk_cols, cap))


def _act_matmul_kernel(x_ref, w_ref, o_ref):
  x = x_ref[...].astype(jnp.float32)                 # [blk_r, F]
  a = _gelu_f32(x)
  w = w_ref[...]                                     # [F, blk_n]
  acc = jax.lax.dot_general(
      a.astype(w.dtype), w, (((1,), (0,)), ((), ())),
      preferred_element_type=jnp.float32)
  o_ref[...] = acc.astype(o_ref.dtype)


def _act_matmul_fwd(x, W, blk_rows, blk_cols, interpret):
  shape = x.shape
  f = shape[-1]
  n = W.shape[-1]
  rows = 1
  for s in shape[:-1]:
    rows *= s
  xf = x.reshape(rows, f)
  blk_r, blk_n = effective_blocks(rows, f, n, blk_rows, blk_cols,
                                  W.dtype.itemsize)

  out = pl.pallas_call(
      _act_matmul_kernel,
      grid=(rows // blk_r, n // blk_n),
      in_specs=[
          pl.BlockSpec((blk_r, f), lambda i, j: (i, 0)),
          pl.BlockSpec((f, blk_n), lambda i, j: (0, j)),
      ],
      out_specs=pl.BlockSpec((blk_r, blk_n), lambda i, j: (i, j)),
      out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
      interpret=interpret,
  )(xf, W)
  return out.reshape(shape[:-1] + (n,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _act_matmul_vjp(x, W, blk_rows, blk_cols, interpret):
  return _act_matmul_fwd(x, W, blk_rows, blk_cols, interpret)


def _fwd_rule(x, W, blk_rows, blk_cols, interpret):
  return _act_matmul_fwd(x, W, blk_rows, blk_cols, interpret), (x, W)


def _bwd_rule(blk_rows, blk_cols, interpret, res, g):
  x, W = res
  shape = x.shape
  f = shape[-1]
  xf = x.reshape(-1, f).astype(jnp.float32)
  gf = g.reshape(-1, W.shape[-1])
  # recompute the activation and its derivative via jax AD (keeps the
  # derivative exactly consistent with the forward's tanh approximation)
  a, gelu_vjp = jax.vjp(_gelu_f32, xf)
  a = a.astype(x.dtype)
  # dW = gelu(x)^T @ g ; dx = (g @ W^T) ⊙ gelu'(x)
  dW = jax.lax.dot_general(a, gf.astype(x.dtype), (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
  ga = jax.lax.dot_general(gf.astype(x.dtype), W, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)
  dx, = gelu_vjp(ga)
  return (dx.reshape(shape).astype(x.dtype), dW.astype(W.dtype))


_act_matmul_vjp.defvjp(_fwd_rule, _bwd_rule)


def gelu_matmul(x, W, blk_rows: int = 128, blk_cols: int = 512,
                interpret: bool = False):
  """``gelu(x) @ W`` with the activated tensor never leaving VMEM.
  x: [..., F] pre-activation; W: [F, N] → [..., N]. Differentiable
  (custom VJP; backward recomputes the activation in XLA)."""
  return _act_matmul_vjp(x, W, blk_rows, blk_cols, interpret)


def gelu_matmul_sharded(x, W, mesh, blk_rows: int = 128,
                        blk_cols: int = 512, interpret: bool = False,
                        batch_axes=None):
  """Fused GELU+matmul applied per-shard through shard_map.

  Unlike :func:`ops.ln_matmul_sharded`, here the CONTRACTED dim (d_ff)
  is the tensor-sharded one in Megatron-style TP: the up-projection
  leaves [rows, F/t] per device, GELU is elementwise-local, and the
  down-projection contracts the local F/t slice — the partial products
  are then summed over the tensor axis (one psum, the same collective
  the unfused down-proj needs, so the fusion adds no communication).

  x: [batch, seq, F] with batch over data(+fsdp), seq over sequence, F
  over tensor (replicated if indivisible); W: [F, N] sharded on F the
  same way; output [batch, seq, N] with N unsharded.
  """
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map
  from jax import lax
  from jax.sharding import PartitionSpec as P
  from tensorflowonspark_tpu.parallel import mesh as mesh_lib

  if batch_axes is None:
    batch_axes = mesh_lib.data_axes(mesh)
  seq_axis = mesh_lib.AXIS_SEQUENCE \
      if mesh_lib.AXIS_SEQUENCE in mesh.axis_names else None
  tensor_axis = mesh_lib.AXIS_TENSOR \
      if mesh_lib.AXIS_TENSOR in mesh.axis_names else None
  if tensor_axis and (x.shape[-1] % mesh.shape[tensor_axis] != 0
                      or mesh.shape[tensor_axis] == 1):
    tensor_axis = None

  def _body(xs, ws):
    part = _act_matmul_vjp(xs, ws, blk_rows, blk_cols, interpret)
    if tensor_axis:
      part = lax.psum(part, tensor_axis)
    return part

  fn = shard_map(
      _body, mesh=mesh,
      in_specs=(P(batch_axes or None, seq_axis, tensor_axis),
                P(tensor_axis, None)),
      out_specs=P(batch_axes or None, seq_axis, None),
      check_vma=False)
  return fn(x, W)
