"""Fused attention as Pallas TPU kernels — forward, backward, and
ring-composable block partials.

Flash-attention-style: the forward streams over K/V blocks with an online
softmax carried in VMEM scratch, so the [Sq, Sk] score matrix never hits
HBM — scores are produced on the MXU, normalized on the VPU, accumulated
in float32 while inputs stay bfloat16. The forward also emits the per-row
logsumexp; the backward (standard Δ correction, dense scores never
materialized) defaults to ONE single-pass kernel producing dQ/dK/dV per
k-block with dQ accumulated in a grid-resident VMEM block — 5 MXU matmuls
per (q, k) block pair; ``TFOS_TPU_FLASH_BWD=split`` (or ``bwd="split"``)
selects the two-kernel plan (dQ over q-blocks, dK/dV over k-blocks; 7
matmuls/pair). Backward block sizes resolve separately from the forward's
(``DEFAULT_BWD_BLOCKS``).

:func:`flash_attention` is full (self-)attention. :func:`flash_attention_block`
computes a PARTIAL attention of local queries against one remote KV block
(absolute position bases passed as traced scalars) and returns
(normalized-partial output, logsumexp) — the building block
``parallel.ring_attention`` merges across ring steps; its custom VJP
accepts cotangents for both outputs (the lse cotangent folds into Δ).

``interpret=True`` runs the same kernels on CPU for tests. Layout:
[batch, seq, heads, head_dim].

Grouped-query attention is native: K/V may carry ``heads / g`` heads (KV
head j serves query heads [j·g, (j+1)·g) — the blocked convention shared
with ``parallel.ring_attention.expand_heads``). The forward and dQ
kernels just remap their KV BlockSpec row (query head → its KV head), so
the grouped block is read straight from HBM with no g× expansion; dK/dV
accumulate across the g query heads inside the grid (see the ``_gqa``
kernels) instead of summing an expanded cotangent.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Per-row statistics (logsumexp, Δ) ride through kernels with this many
# trailing lanes: Mosaic's layout verifier rejects blocked 1-D operands and
# (1, blk) blocks of 2-D arrays, but a [rows, LANES] array blocked
# (blk, LANES) satisfies the (8, 128)-or-full-dim tiling rule with 16×
# less padding than a full 128-lane broadcast.
LANES = 8


def _masked_scores(q, k, qi, ki, blk_q, blk_k, causal, q_base=0, k_base=0,
                   window=None):
  """Scaled scores for one (q-block, k-block) pair with causal masking.

  ``q_base``/``k_base`` are absolute position offsets (traced scalars are
  fine) so the same kernel works for ring-attention blocks where the KV
  block comes from another sequence shard. ``window`` (sliding-window
  attention, Mistral convention: each query attends to the ``window``
  most recent positions including itself) additionally masks
  ``k_pos <= q_pos - window``; the loop-bound helpers below skip blocks
  the mask would zero entirely, so FLOPs scale with the window, not the
  sequence.
  """
  s = q @ k.astype(jnp.float32).T
  if causal:
    q_pos = q_base + qi * blk_q + lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)
    k_pos = k_base + ki * blk_k + lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 1)
    keep = k_pos <= q_pos
    if window is not None:
      keep = jnp.logical_and(keep, k_pos > q_pos - window)
    s = jnp.where(keep, s, NEG_INF)
  return s


def _causal_k_hi(qi, q_base, k_base, blk_q, blk_k, n_kblocks):
  """Exclusive upper bound on k-blocks visible to q-block ``qi`` under the
  causal mask — blocks past the diagonal are fully masked, so the online-
  softmax loop skips them instead of exp()-ing NEG_INF (≈2× FLOPs saved
  at equal bases; rides the ring offsets for sequence parallelism)."""
  q_hi = q_base + (qi + 1) * blk_q - 1      # max absolute q position
  return jnp.clip((q_hi - k_base) // blk_k + 1, 0, n_kblocks)


def _window_k_lo(qi, q_base, k_base, blk_q, blk_k, window, n_kblocks):
  """First k-block with any position inside q-block ``qi``'s window —
  the lower loop bound that makes sliding-window FLOPs O(window)."""
  k_lo = q_base + qi * blk_q - (window - 1) - k_base   # min visible k pos
  return jnp.clip(k_lo // blk_k, 0, n_kblocks)


def _causal_q_lo(ki, q_base, k_base, blk_q, blk_k):
  """First q-block with any row at-or-past k-block ``ki``'s start."""
  k_lo = k_base + ki * blk_k - q_base       # min k position, q-relative
  return jnp.clip(k_lo // blk_q, 0, None)


def _window_q_hi(ki, q_base, k_base, blk_q, blk_k, window, n_qblocks):
  """Exclusive upper bound on q-blocks that can still see k-block ``ki``
  under a sliding window (rows further ahead have slid past it)."""
  q_hi = k_base + (ki + 1) * blk_k - 1 + (window - 1) - q_base
  return jnp.clip(q_hi // blk_q + 1, 0, n_qblocks)


def _pair_p_ds(s, lse, delta, do, v):
  """Shared backward math for one (q, k) block pair — P recomputed from
  the forward's logsumexp (fully-masked rows/entries forced to 0), then
  dP = dO·Vᵀ and dS = P ⊙ (dP − Δ). Used by all three backward kernels
  (dQ, dK/dV, fused) so a masking/Δ fix lands everywhere at once."""
  lse_safe = jnp.where(lse <= NEG_INF, 0.0, lse)
  p = jnp.exp(s - lse_safe)
  p = jnp.where(jnp.logical_or(s <= NEG_INF, lse <= NEG_INF), 0.0, p)
  ds = p * (do @ v.T - delta)
  return p, ds


# --- kernels ---------------------------------------------------------------


def _attn_fwd_kernel(qb_ref, kb_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                     blk_q: int, blk_k: int, kv_len: int, causal: bool,
                     scale: float, window=None):
  qi = pl.program_id(1)
  q_base = qb_ref[0]
  k_base = kb_ref[0]
  q = q_ref[0].astype(jnp.float32) * scale          # [blk_q, D]
  n_kblocks = kv_len // blk_k

  def body(ki, carry):
    m, l, acc = carry                               # [blk_q,1] ×2, [blk_q,D]
    # block loads straight from VMEM refs — dynamic_slice on a loaded
    # value has no Mosaic lowering
    k = k_ref[0, pl.ds(ki * blk_k, blk_k), :]
    v = v_ref[0, pl.ds(ki * blk_k, blk_k), :]
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal, q_base, k_base,
                       window)
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe)
    p = jnp.where(s <= NEG_INF, 0.0, p)
    corr = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + p @ v.astype(jnp.float32)
    return m_new, l_new, acc_new

  m0 = jnp.full((blk_q, 1), NEG_INF, jnp.float32)
  l0 = jnp.zeros((blk_q, 1), jnp.float32)
  acc0 = jnp.zeros((blk_q, q.shape[-1]), jnp.float32)
  hi = _causal_k_hi(qi, q_base, k_base, blk_q, blk_k, n_kblocks) \
      if causal else n_kblocks
  lo = _window_k_lo(qi, q_base, k_base, blk_q, blk_k, window, n_kblocks) \
      if window is not None else 0
  m, l, acc = lax.fori_loop(lo, hi, body, (m0, l0, acc0))

  l_safe = jnp.where(l == 0.0, 1.0, l)
  o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
  lse_col = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))  # [blk_q, 1]
  lse_ref[0] = jnp.broadcast_to(lse_col, (blk_q, LANES))


def _attn_bwd_dq_kernel(qb_ref, kb_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, dq_ref, *, blk_q: int, blk_k: int,
                        kv_len: int, causal: bool, scale: float,
                        window=None):
  """dQ for one q-block: dQ = scale · Σ_k [P ⊙ (dO·Vᵀ − Δ)] · K."""
  qi = pl.program_id(1)
  q_base = qb_ref[0]
  k_base = kb_ref[0]
  q = q_ref[0].astype(jnp.float32) * scale
  do = do_ref[0].astype(jnp.float32)                # [blk_q, D]
  lse = lse_ref[0][:, 0:1]                          # [blk_q, 1]
  delta = delta_ref[0][:, 0:1]                      # [blk_q, 1]
  n_kblocks = kv_len // blk_k

  def body(ki, dq):
    k = k_ref[0, pl.ds(ki * blk_k, blk_k), :]
    v = v_ref[0, pl.ds(ki * blk_k, blk_k), :]
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal, q_base, k_base,
                       window)
    _, ds = _pair_p_ds(s, lse, delta, do, v.astype(jnp.float32))
    return dq + ds @ k.astype(jnp.float32)

  dq0 = jnp.zeros((blk_q, q.shape[-1]), jnp.float32)
  hi = _causal_k_hi(qi, q_base, k_base, blk_q, blk_k, n_kblocks) \
      if causal else n_kblocks
  lo = _window_k_lo(qi, q_base, k_base, blk_q, blk_k, window, n_kblocks) \
      if window is not None else 0
  dq = lax.fori_loop(lo, hi, body, dq0)
  dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(qb_ref, kb_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dk_ref, dv_ref, *, blk_q: int,
                         blk_k: int, q_len: int, causal: bool,
                         scale: float, window=None):
  """dK/dV for one k-block: dV = Σ_q Pᵀ·dO; dK = scale · Σ_q dSᵀ·Q."""
  ki = pl.program_id(1)
  q_base = qb_ref[0]
  k_base = kb_ref[0]
  k = k_ref[0].astype(jnp.float32)                  # [blk_k, D]
  v = v_ref[0].astype(jnp.float32)
  n_qblocks = q_len // blk_q

  def body(qi, carry):
    dk, dv = carry
    q = q_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32) * scale
    do = do_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
    lse = lse_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    delta = delta_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal, q_base, k_base,
                       window)
    p, ds = _pair_p_ds(s, lse, delta, do, v)
    dv_new = dv + p.T @ do
    dk_new = dk + ds.T @ q
    return dk_new, dv_new

  dk0 = jnp.zeros((blk_k, k.shape[-1]), jnp.float32)
  dv0 = jnp.zeros((blk_k, v.shape[-1]), jnp.float32)
  lo = _causal_q_lo(ki, q_base, k_base, blk_q, blk_k) if causal else 0
  hi = _window_q_hi(ki, q_base, k_base, blk_q, blk_k, window, n_qblocks) \
      if window is not None else n_qblocks
  dk, dv = lax.fori_loop(lo, hi, body, (dk0, dv0))
  dk_ref[0] = dk.astype(dk_ref.dtype)   # q was pre-scaled; dk absorbs it
  dv_ref[0] = dv.astype(dv_ref.dtype)


def _attn_bwd_fused_kernel(qb_ref, kb_ref, q_ref, k_ref, v_ref, do_ref,
                           lse_ref, delta_ref, dq_ref, dk_ref, dv_ref, *,
                           blk_q: int, blk_k: int, q_len: int, causal: bool,
                           scale: float, window=None):
  """Single-pass backward: dK/dV for one k-block plus this k-block's dQ
  contributions, accumulated into a grid-resident full-sequence dQ output.

  The dQ output's index map ignores the k-grid index, so Mosaic keeps the
  block in VMEM across the sequential k steps (zeroed at ki == 0, flushed
  when the batch·head index advances). Scores/probabilities are computed
  once per (q, k) block pair instead of once in each of two kernels: 5
  MXU matmuls per pair vs 7 for the split backward.
  """
  ki = pl.program_id(1)
  q_base = qb_ref[0]
  k_base = kb_ref[0]
  k = k_ref[0].astype(jnp.float32)                  # [blk_k, D]
  v = v_ref[0].astype(jnp.float32)
  n_qblocks = q_len // blk_q

  @pl.when(ki == 0)
  def _zero_dq():  # noqa: ANN202 - pallas region
    dq_ref[0] = jnp.zeros_like(dq_ref[0])

  def body(qi, carry):
    dk, dv = carry
    q = q_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32) * scale
    do = do_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
    lse = lse_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    delta = delta_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal, q_base, k_base,
                       window)
    p, ds = _pair_p_ds(s, lse, delta, do, v)
    dv_new = dv + p.T @ do
    dk_new = dk + ds.T @ q                          # q pre-scaled: absorbs it
    prev = dq_ref[0, pl.ds(qi * blk_q, blk_q), :]
    dq_ref[0, pl.ds(qi * blk_q, blk_q), :] = prev + (ds @ k) * scale
    return dk_new, dv_new

  dk0 = jnp.zeros((blk_k, k.shape[-1]), jnp.float32)
  dv0 = jnp.zeros((blk_k, v.shape[-1]), jnp.float32)
  lo = _causal_q_lo(ki, q_base, k_base, blk_q, blk_k) if causal else 0
  hi = _window_q_hi(ki, q_base, k_base, blk_q, blk_k, window, n_qblocks) \
      if window is not None else n_qblocks
  dk, dv = lax.fori_loop(lo, hi, body, (dk0, dv0))
  dk_ref[0] = dk.astype(dk_ref.dtype)
  dv_ref[0] = dv.astype(dv_ref.dtype)


def _attn_bwd_dkv_gqa_kernel(qb_ref, kb_ref, q_ref, k_ref, v_ref, do_ref,
                             lse_ref, delta_ref, dk_ref, dv_ref, *,
                             blk_q: int, blk_k: int, q_len: int,
                             causal: bool, scale: float, window=None):
  """Grouped-KV dK/dV: grid (b·kv_heads, n_kblocks, group).

  The group axis is INNERMOST, so each (blk_k, D) dK/dV block stays
  VMEM-resident while its g query heads sweep past, accumulating into it
  in f32 (assigned at qh == 0, read-modify-write after) — cross-head
  accumulation in the grid instead of expanding K/V g× through HBM and
  summing an expanded cotangent outside. Per-(q, k) block math is
  identical to :func:`_attn_bwd_dkv_kernel`; the q/do/lse/delta
  BlockSpecs select the current query head's row.
  """
  ki = pl.program_id(1)
  qh = pl.program_id(2)
  q_base = qb_ref[0]
  k_base = kb_ref[0]
  k = k_ref[0].astype(jnp.float32)                  # [blk_k, D]
  v = v_ref[0].astype(jnp.float32)
  n_qblocks = q_len // blk_q

  def body(qi, carry):
    dk, dv = carry
    q = q_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32) * scale
    do = do_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
    lse = lse_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    delta = delta_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal, q_base, k_base,
                       window)
    p, ds = _pair_p_ds(s, lse, delta, do, v)
    return dk + ds.T @ q, dv + p.T @ do

  dk0 = jnp.zeros((blk_k, k.shape[-1]), jnp.float32)
  dv0 = jnp.zeros((blk_k, v.shape[-1]), jnp.float32)
  lo = _causal_q_lo(ki, q_base, k_base, blk_q, blk_k) if causal else 0
  hi = _window_q_hi(ki, q_base, k_base, blk_q, blk_k, window, n_qblocks) \
      if window is not None else n_qblocks
  dk, dv = lax.fori_loop(lo, hi, body, (dk0, dv0))

  @pl.when(qh == 0)
  def _assign():  # noqa: ANN202 - pallas region
    dk_ref[0] = dk
    dv_ref[0] = dv

  @pl.when(qh != 0)
  def _accumulate():  # noqa: ANN202 - pallas region
    dk_ref[0] = dk_ref[0] + dk
    dv_ref[0] = dv_ref[0] + dv


def _attn_bwd_fused_gqa_kernel(qb_ref, kb_ref, q_ref, k_ref, v_ref, do_ref,
                               lse_ref, delta_ref, dq_ref, dk_ref, dv_ref, *,
                               blk_q: int, blk_k: int, q_len: int,
                               causal: bool, scale: float, window=None):
  """Grouped-KV single-pass backward: grid (b·kv_heads, group, n_kblocks).

  dQ of the current query head accumulates across the innermost k-block
  axis (zeroed at ki == 0) exactly like the MHA fused kernel. dK/dV are
  FULL [s_kv, D] f32 blocks resident across the whole (group, k-block)
  sweep; each step read-modify-writes only its blk_k-row slice, assigning
  at qh == 0 and accumulating after. VMEM ≈ (2·s_kv + s_q)·D·4B for the
  residents — :func:`_gqa_fused_fits` guards it and callers fall back to
  the split plan when it exceeds the budget.
  """
  qh = pl.program_id(1)
  ki = pl.program_id(2)
  q_base = qb_ref[0]
  k_base = kb_ref[0]
  k = k_ref[0].astype(jnp.float32)                  # [blk_k, D]
  v = v_ref[0].astype(jnp.float32)
  n_qblocks = q_len // blk_q

  @pl.when(ki == 0)
  def _zero_dq():  # noqa: ANN202 - pallas region
    dq_ref[0] = jnp.zeros_like(dq_ref[0])

  def body(qi, carry):
    dk, dv = carry
    q = q_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32) * scale
    do = do_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
    lse = lse_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    delta = delta_ref[0, pl.ds(qi * blk_q, blk_q), 0:1]
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal, q_base, k_base,
                       window)
    p, ds = _pair_p_ds(s, lse, delta, do, v)
    dv_new = dv + p.T @ do
    dk_new = dk + ds.T @ q                          # q pre-scaled: absorbs it
    prev = dq_ref[0, pl.ds(qi * blk_q, blk_q), :]
    dq_ref[0, pl.ds(qi * blk_q, blk_q), :] = prev + (ds @ k) * scale
    return dk_new, dv_new

  dk0 = jnp.zeros((blk_k, k.shape[-1]), jnp.float32)
  dv0 = jnp.zeros((blk_k, v.shape[-1]), jnp.float32)
  lo = _causal_q_lo(ki, q_base, k_base, blk_q, blk_k) if causal else 0
  hi = _window_q_hi(ki, q_base, k_base, blk_q, blk_k, window, n_qblocks) \
      if window is not None else n_qblocks
  dk, dv = lax.fori_loop(lo, hi, body, (dk0, dv0))

  sl = pl.ds(ki * blk_k, blk_k)

  @pl.when(qh == 0)
  def _assign():  # noqa: ANN202 - pallas region
    dk_ref[0, sl, :] = dk
    dv_ref[0, sl, :] = dv

  @pl.when(qh != 0)
  def _accumulate():  # noqa: ANN202 - pallas region
    dk_ref[0, sl, :] = dk_ref[0, sl, :] + dk
    dv_ref[0, sl, :] = dv_ref[0, sl, :] + dv


# VMEM budget for the grouped fused backward's resident blocks (dK+dV full
# f32 + dQ f32 + q/do); past this the split plan wins anyway because the
# residents crowd out double-buffering for the streamed blocks
GQA_FUSED_VMEM_BUDGET = 10 * 1024 * 1024


def _gqa_fused_fits(s_q: int, s_kv: int, d: int, itemsize: int) -> bool:
  resident = (2 * s_kv + s_q) * d * 4 + 2 * s_q * d * itemsize
  return resident <= GQA_FUSED_VMEM_BUDGET


# --- shared impl -----------------------------------------------------------


def _blocks(s_q, s_kv, blk_q, blk_k):
  """Clamp block sizes so any sequence length works without padding.

  Mosaic accepts a sublane block only if it is a multiple of 8 or equal
  to the full dimension, so shrink to the largest divisor of ``s`` that
  is a multiple of 8; when no such divisor exists (e.g. s = 2·499) fall
  back to one full-dimension block rather than a tiny degenerate one.
  """
  def _fit(blk, s):
    blk = min(blk, s)
    while blk > 0:
      if s % blk == 0 and (blk % 8 == 0 or blk == s):
        return blk
      blk -= 1
    return s
  return _fit(blk_q, s_q), _fit(blk_k, s_kv)


def _fold(x):
  b, s, h, d = x.shape
  return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, h):
  bh, s, d = x.shape
  return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _base_arrays(q_base, kv_base):
  """Position bases as (1,)-shaped int32 scalar-prefetch operands.

  Traced scalars (ring attention derives them from ``lax.axis_index``)
  ride to the kernel through SMEM via ``PrefetchScalarGridSpec`` — 1-D
  blocked VMEM operands fail Mosaic layout verification on real TPUs.
  """
  qb = jnp.reshape(jnp.asarray(q_base, jnp.int32), (1,))
  kb = jnp.reshape(jnp.asarray(kv_base, jnp.int32), (1,))
  return qb, kb


def _group(q, k):
  """(kv_heads, group) from q/k head counts, validating divisibility."""
  h, hk = q.shape[2], k.shape[2]
  if h % hk:
    raise ValueError("kv heads (%d) must divide query heads (%d)"
                     % (hk, h))
  return hk, h // hk


def _kv_row_map(h, hk, g):
  """KV BlockSpec row for folded-query-row ``i``: query head i%h reads
  its group's KV head — the grouped-aware index map that lets the kernels
  consume unexpanded K/V (g == 1 degenerates to row i)."""
  return lambda i, j, *_: ((i // h) * hk + (i % h) // g, 0, 0)


def _q_row_map(h, hk, grp, qh_axis):
  """Query-row BlockSpec map for the grouped (b·hk)-rooted grids: grid
  dim 0 is the folded KV row, grid dim ``qh_axis`` the head-in-group
  position; the map selects that query head's folded row. ONE definition
  for both grouped backward plans so the blocked grouping convention
  (KV head j serves query heads [j·g, (j+1)·g)) cannot drift between
  them."""
  def _map(*idx):
    i, qh = idx[0], idx[qh_axis]
    return ((i // hk) * h + (i % hk) * grp + qh, 0, 0)
  return _map


def _check_window(window, causal):
  if window is None:
    return None
  window = int(window)
  if window < 1:
    raise ValueError("window must be >= 1, got %d" % window)
  if not causal:
    raise ValueError("sliding-window attention requires causal=True "
                     "(the window is 'the last W positions')")
  return window


@functools.partial(jax.jit, static_argnames=("causal", "blk_q", "blk_k",
                                             "interpret", "window"))
def _fwd_impl(q, k, v, q_base, kv_base, causal, blk_q, blk_k, interpret,
              window=None):
  b, s_q, h, d = q.shape
  s_kv = k.shape[1]
  hk, g = _group(q, k)
  blk_q, blk_k = _blocks(s_q, s_kv, blk_q, blk_k)
  scale = 1.0 / (d ** 0.5)
  qf, kf, vf = _fold(q), _fold(k), _fold(v)
  qb, kb = _base_arrays(q_base, kv_base)

  kernel = functools.partial(_attn_fwd_kernel, blk_q=blk_q, blk_k=blk_k,
                             kv_len=s_kv, causal=causal, scale=scale,
                             window=_check_window(window, causal))
  out, lse = pl.pallas_call(
      kernel,
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=2,
          grid=(b * h, s_q // blk_q),
          in_specs=[
              pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
              pl.BlockSpec((1, s_kv, d), _kv_row_map(h, hk, g)),
              pl.BlockSpec((1, s_kv, d), _kv_row_map(h, hk, g)),
          ],
          out_specs=[
              pl.BlockSpec((1, blk_q, d), lambda i, j, *_: (i, j, 0)),
              pl.BlockSpec((1, blk_q, LANES), lambda i, j, *_: (i, j, 0)),
          ],
      ),
      out_shape=[
          jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
          jax.ShapeDtypeStruct((b * h, s_q, LANES), jnp.float32),
      ],
      interpret=interpret,
  )(qb, kb, qf, kf, vf)

  return _unfold(out, b, h), lse[:, :, 0].reshape(b, h, s_q)


def default_bwd_mode() -> str:
  """Backward kernel selection: ``fused`` (single-pass, default) or
  ``split`` (separate dQ and dK/dV kernels) via ``TFOS_TPU_FLASH_BWD``."""
  import os
  mode = os.environ.get("TFOS_TPU_FLASH_BWD", "fused")
  if mode not in ("fused", "split"):
    raise ValueError("TFOS_TPU_FLASH_BWD must be 'fused' or 'split', got %r"
                     % (mode,))
  return mode


# The backward prefers different tiles than the forward (v5e fetch-timed
# sweeps at b4 s4096 h8 d128): the fused single-pass kernel wants smaller
# q-blocks — each (q,k) pair read-modify-writes a blk_q-row slice of the
# resident dQ accumulator, and 128 rows keeps that RMW on the critical
# path shorter — while the split kernels match the forward's (256, 512).
# Resolved per-mode inside _bwd_impl — AFTER its VMEM fallback may have
# switched fused→split, so a fallback under default tuning picks up the
# split plan's blocks (an early comparison against the fused defaults
# would miss whenever _blocks had already clamped them for short
# sequences). Override with blk_bwd_q/blk_bwd_k (kept None = defaults).
DEFAULT_BWD_BLOCKS = {"fused": (128, 512), "split": (256, 512)}


def _resolve_bwd(bwd):
  """Validate/default the backward mode (block tuning resolves later,
  see DEFAULT_BWD_BLOCKS)."""
  bwd = bwd or default_bwd_mode()
  if bwd not in DEFAULT_BWD_BLOCKS:
    raise ValueError("bwd must be 'fused' or 'split', got %r" % (bwd,))
  return bwd


@functools.partial(jax.jit, static_argnames=("causal", "blk_q", "blk_k",
                                             "interpret", "bwd", "window"))
def _bwd_impl(q, k, v, out, lse, g, g_lse, q_base, kv_base, causal, blk_q,
              blk_k, interpret, bwd="fused", window=None):
  window = _check_window(window, causal)
  b, s_q, h, d = q.shape
  s_kv = k.shape[1]
  hk, grp = _group(q, k)
  scale = 1.0 / (d ** 0.5)
  qf, of, gf = (_fold(x) for x in (q, out, g))
  kf, vf = _fold(k), _fold(v)
  qb, kb = _base_arrays(q_base, kv_base)

  # Δ_i = Σ_d dO·O  (+ the lse cotangent folds in with opposite sign:
  # dS = P ⊙ (dP − Δ + g_lse))
  delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
  if g_lse is not None:
    delta = delta - g_lse.reshape(b * h, s_q)
  # lse/Δ enter the kernels lane-broadcast (see LANES)
  lse_f = jnp.broadcast_to(lse.reshape(b * h, s_q)[:, :, None],
                           (b * h, s_q, LANES))
  delta = jnp.broadcast_to(delta[:, :, None], (b * h, s_q, LANES))

  full3 = lambda i, j, *_: (i, 0, 0)      # noqa: E731
  row3 = lambda i, j, *_: (i, j, 0)       # noqa: E731
  kvfull = _kv_row_map(h, hk, grp)        # query row i -> its KV head's row

  if bwd == "fused" and grp > 1 and not _gqa_fused_fits(
      s_q, s_kv, d, q.dtype.itemsize):
    bwd = "split"   # resident dK/dV would not fit VMEM; split plan wins
  # block defaults resolve AFTER the fallback so a fused→split switch
  # gets split tuning; explicit caller overrides (non-None) are untouched
  dq_def, dk_def = DEFAULT_BWD_BLOCKS[bwd]
  blk_q = dq_def if blk_q is None else blk_q
  blk_k = dk_def if blk_k is None else blk_k
  blk_q, blk_k = _blocks(s_q, s_kv, blk_q, blk_k)

  if bwd == "fused" and grp > 1:
    qrow = _q_row_map(h, hk, grp, qh_axis=1)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_attn_bwd_fused_gqa_kernel, blk_q=blk_q,
                          blk_k=blk_k, q_len=s_q, causal=causal,
                          scale=scale, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * hk, grp, s_kv // blk_k),
            in_specs=[
                pl.BlockSpec((1, s_q, d), qrow),
                pl.BlockSpec((1, blk_k, d),
                             lambda i, qh, ki, *_: (i, ki, 0)),
                pl.BlockSpec((1, blk_k, d),
                             lambda i, qh, ki, *_: (i, ki, 0)),
                pl.BlockSpec((1, s_q, d), qrow),
                pl.BlockSpec((1, s_q, LANES), qrow),
                pl.BlockSpec((1, s_q, LANES), qrow),
            ],
            out_specs=[
                pl.BlockSpec((1, s_q, d), qrow),    # dQ: resident across ki
                pl.BlockSpec((1, s_kv, d),
                             lambda i, qh, ki, *_: (i, 0, 0)),
                pl.BlockSpec((1, s_kv, d),
                             lambda i, qh, ki, *_: (i, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_q, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, s_kv, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, s_kv, d), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, qf, kf, vf, gf, lse_f, delta)
    return (_unfold(dq, b, h).astype(q.dtype),
            _unfold(dk, b, hk).astype(k.dtype),
            _unfold(dv, b, hk).astype(v.dtype))

  if bwd == "fused":
    dq, dk, dv = pl.pallas_call(
        functools.partial(_attn_bwd_fused_kernel, blk_q=blk_q, blk_k=blk_k,
                          q_len=s_q, causal=causal, scale=scale, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * h, s_kv // blk_k),
            in_specs=[
                pl.BlockSpec((1, s_q, d), full3),
                pl.BlockSpec((1, blk_k, d), row3),
                pl.BlockSpec((1, blk_k, d), row3),
                pl.BlockSpec((1, s_q, d), full3),
                pl.BlockSpec((1, s_q, LANES), full3),
                pl.BlockSpec((1, s_q, LANES), full3),
            ],
            out_specs=[
                pl.BlockSpec((1, s_q, d), full3),   # dQ: resident across ki
                pl.BlockSpec((1, blk_k, d), row3),
                pl.BlockSpec((1, blk_k, d), row3),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_q, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, s_kv, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s_kv, d), v.dtype),
        ],
        interpret=interpret,
    )(qb, kb, qf, kf, vf, gf, lse_f, delta)
    return (_unfold(dq, b, h).astype(q.dtype), _unfold(dk, b, h),
            _unfold(dv, b, h))

  dq = pl.pallas_call(
      functools.partial(_attn_bwd_dq_kernel, blk_q=blk_q, blk_k=blk_k,
                        kv_len=s_kv, causal=causal, scale=scale, window=window),
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=2,
          grid=(b * h, s_q // blk_q),
          in_specs=[
              pl.BlockSpec((1, blk_q, d), row3),
              pl.BlockSpec((1, s_kv, d), kvfull),
              pl.BlockSpec((1, s_kv, d), kvfull),
              pl.BlockSpec((1, blk_q, d), row3),
              pl.BlockSpec((1, blk_q, LANES), row3),
              pl.BlockSpec((1, blk_q, LANES), row3),
          ],
          out_specs=pl.BlockSpec((1, blk_q, d), row3),
      ),
      out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
      interpret=interpret,
  )(qb, kb, qf, kf, vf, gf, lse_f, delta)

  if grp > 1:
    qrow = _q_row_map(h, hk, grp, qh_axis=2)
    dk, dv = pl.pallas_call(
        functools.partial(_attn_bwd_dkv_gqa_kernel, blk_q=blk_q,
                          blk_k=blk_k, q_len=s_q, causal=causal,
                          scale=scale, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * hk, s_kv // blk_k, grp),
            in_specs=[
                pl.BlockSpec((1, s_q, d), qrow),
                pl.BlockSpec((1, blk_k, d),
                             lambda i, ki, qh, *_: (i, ki, 0)),
                pl.BlockSpec((1, blk_k, d),
                             lambda i, ki, qh, *_: (i, ki, 0)),
                pl.BlockSpec((1, s_q, d), qrow),
                pl.BlockSpec((1, s_q, LANES), qrow),
                pl.BlockSpec((1, s_q, LANES), qrow),
            ],
            out_specs=[
                # resident across the innermost group sweep
                pl.BlockSpec((1, blk_k, d),
                             lambda i, ki, qh, *_: (i, ki, 0)),
                pl.BlockSpec((1, blk_k, d),
                             lambda i, ki, qh, *_: (i, ki, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * hk, s_kv, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, s_kv, d), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, qf, kf, vf, gf, lse_f, delta)
    return (_unfold(dq, b, h), _unfold(dk, b, hk).astype(k.dtype),
            _unfold(dv, b, hk).astype(v.dtype))

  dk, dv = pl.pallas_call(
      functools.partial(_attn_bwd_dkv_kernel, blk_q=blk_q, blk_k=blk_k,
                        q_len=s_q, causal=causal, scale=scale, window=window),
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=2,
          grid=(b * h, s_kv // blk_k),
          in_specs=[
              pl.BlockSpec((1, s_q, d), full3),
              pl.BlockSpec((1, blk_k, d), row3),
              pl.BlockSpec((1, blk_k, d), row3),
              pl.BlockSpec((1, s_q, d), full3),
              pl.BlockSpec((1, s_q, LANES), full3),
              pl.BlockSpec((1, s_q, LANES), full3),
          ],
          out_specs=[
              pl.BlockSpec((1, blk_k, d), row3),
              pl.BlockSpec((1, blk_k, d), row3),
          ],
      ),
      out_shape=[
          jax.ShapeDtypeStruct((b * h, s_kv, d), k.dtype),
          jax.ShapeDtypeStruct((b * h, s_kv, d), v.dtype),
      ],
      interpret=interpret,
  )(qb, kb, qf, kf, vf, gf, lse_f, delta)

  return _unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h)


# --- public: full attention -------------------------------------------------


def flash_attention(q, k, v, causal: bool = True, blk_q: int = 256,
                    blk_k: int = 512, interpret: bool = False,
                    bwd: str = None, blk_bwd_q: int = None,
                    blk_bwd_k: int = None, window: int = None):
  """Fused (self-)attention with fused backward. q: [batch, seq, heads,
  head_dim]; k/v: same, or with heads/g KV heads (grouped-query
  attention — consumed unexpanded, see module docstring); seq must
  divide by the (clamped) block sizes. ``bwd``: 'fused' (single-pass
  dQ/dK/dV) or 'split' (two kernels); defaults to
  :func:`default_bwd_mode`. The backward uses its own block sizes
  (``DEFAULT_BWD_BLOCKS`` per mode unless overridden). ``window``
  (requires causal) restricts each query to its last ``window``
  positions (sliding-window attention); the kernels' block loops bound
  to the window, so attention FLOPs become O(seq·window) instead of
  O(seq²)."""
  return _flash_vjp(q, k, v, causal, blk_q, blk_k, interpret,
                    _resolve_bwd(bwd), blk_bwd_q, blk_bwd_k, window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_vjp(q, k, v, causal, blk_q, blk_k, interpret, bwd, blk_bwd_q,
               blk_bwd_k, window):
  out, _ = _fwd_impl(q, k, v, 0, 0, causal, blk_q, blk_k, interpret,
                     window)
  return out


def _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret, bwd, blk_bwd_q,
               blk_bwd_k, window):
  out, lse = _fwd_impl(q, k, v, 0, 0, causal, blk_q, blk_k, interpret,
                       window)
  return out, (q, k, v, out, lse)


def _flash_bwd(causal, blk_q, blk_k, interpret, bwd, blk_bwd_q, blk_bwd_k,
               window, residuals, g):
  q, k, v, out, lse = residuals
  return _bwd_impl(q, k, v, out, lse, g, None, 0, 0, causal, blk_bwd_q,
                   blk_bwd_k, interpret, bwd, window)


_flash_vjp.defvjp(_flash_fwd, _flash_bwd)


# --- public: ring-composable block partial ----------------------------------


def flash_attention_block(q, k, v, q_base, kv_base, causal: bool = True,
                          blk_q: int = 256, blk_k: int = 512,
                          interpret: bool = False, bwd: str = None,
                          blk_bwd_q: int = None, blk_bwd_k: int = None,
                          window: int = None):
  """Partial attention of local queries against ONE KV block.

  q: [B, Sq, H, D] at absolute positions ``q_base + arange(Sq)``;
  k/v: [B, Sk, H, D] — or [B, Sk, H/g, D] grouped (GQA), consumed
  unexpanded — at ``kv_base + arange(Sk)`` (bases may be traced —
  inside shard_map they depend on ``lax.axis_index``). Returns
  (normalized partial output, logsumexp) — merge partials across blocks
  with :func:`merge_partials`. Differentiable in q/k/v (including through
  the lse output). ``window`` composes with the ring: a KV block entirely
  behind the window collapses to zero loop iterations (the bounds are
  computed from the traced bases), so out-of-window ring steps cost only
  the kernel launch and the merge.
  """
  return _flash_block_vjp(q, k, v, q_base, kv_base, causal, blk_q, blk_k,
                          interpret, _resolve_bwd(bwd), blk_bwd_q,
                          blk_bwd_k, window)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12))
def _flash_block_vjp(q, k, v, q_base, kv_base, causal, blk_q, blk_k,
                     interpret, bwd, blk_bwd_q, blk_bwd_k, window):
  return _fwd_impl(q, k, v, q_base, kv_base, causal, blk_q, blk_k,
                   interpret, window)


def _flash_block_fwd(q, k, v, q_base, kv_base, causal, blk_q, blk_k,
                     interpret, bwd, blk_bwd_q, blk_bwd_k, window):
  out, lse = _fwd_impl(q, k, v, q_base, kv_base, causal, blk_q, blk_k,
                       interpret, window)
  return (out, lse), (q, k, v, out, lse, q_base, kv_base)


def _flash_block_bwd(causal, blk_q, blk_k, interpret, bwd, blk_bwd_q,
                     blk_bwd_k, window, residuals, cotangents):
  q, k, v, out, lse, q_base, kv_base = residuals
  g, g_lse = cotangents
  dq, dk, dv = _bwd_impl(q, k, v, out, lse, g, g_lse, q_base, kv_base,
                         causal, blk_bwd_q, blk_bwd_k, interpret, bwd,
                         window)
  zero_base = np.zeros((), jax.dtypes.float0)
  return dq, dk, dv, zero_base, zero_base


_flash_block_vjp.defvjp(_flash_block_fwd, _flash_block_bwd)


def merge_partials(o_a, lse_a, o_b, lse_b):
  """Combine two normalized attention partials (the ring-merge step).

  Given partial outputs over disjoint KV sets with their logsumexps,
  produces the exact partial over the union. Fully-masked partials
  (lse = NEG_INF) contribute nothing.
  """
  lse_new = jnp.logaddexp(lse_a, lse_b)               # [B, H, S]
  lse_safe = jnp.where(lse_new <= NEG_INF, 0.0, lse_new)
  w_a = jnp.where((lse_a <= NEG_INF)[..., None], 0.0,
                  jnp.exp(lse_a - lse_safe)[..., None])
  w_b = jnp.where((lse_b <= NEG_INF)[..., None], 0.0,
                  jnp.exp(lse_b - lse_safe)[..., None])
  # weights are [B,H,S,1]; outputs are [B,S,H,D]
  w_a = jnp.swapaxes(w_a, 1, 2)
  w_b = jnp.swapaxes(w_b, 1, 2)
  o = o_a.astype(jnp.float32) * w_a + o_b.astype(jnp.float32) * w_b
  return o.astype(o_a.dtype), lse_new
