"""Fused causal attention as a Pallas TPU kernel.

Flash-attention-style: the kernel streams over K/V blocks with an online
softmax carried in VMEM scratch, so the [S, S] score matrix never hits HBM
— scores are produced on the MXU, normalized on the VPU, and accumulated in
float32 while inputs stay bfloat16.

Grid: one program per (batch*heads, q-block). K/V blocks are looped inside
the kernel with ``lax.fori_loop`` (static shapes, compiler-friendly).

``interpret=True`` runs the same kernel on CPU for tests; on TPU the
MXU/VPU path is used. Layout: [batch, seq, heads, head_dim] to match
``parallel.ring_attention``.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q: int, blk_k: int,
                 seq_len: int, causal: bool, scale: float):
  qi = pl.program_id(1)
  q = q_ref[0].astype(jnp.float32) * scale          # [blk_q, D]
  n_kblocks = seq_len // blk_k

  def body(ki, carry):
    m, l, acc = carry
    k = lax.dynamic_slice_in_dim(k_ref[0], ki * blk_k, blk_k, 0)
    v = lax.dynamic_slice_in_dim(v_ref[0], ki * blk_k, blk_k, 0)
    s = q @ k.astype(jnp.float32).T                 # [blk_q, blk_k] on MXU
    if causal:
      q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32,
                                                (blk_q, blk_k), 0)
      k_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32,
                                                (blk_q, blk_k), 1)
      s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe[:, None])
    p = jnp.where(s <= NEG_INF, 0.0, p)
    corr = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[:, None] + p @ v.astype(jnp.float32)
    return m_new, l_new, acc_new

  m0 = jnp.full((blk_q,), NEG_INF, jnp.float32)
  l0 = jnp.zeros((blk_q,), jnp.float32)
  acc0 = jnp.zeros((blk_q, q.shape[-1]), jnp.float32)

  # causal: blocks strictly right of this q-block's diagonal contribute
  # nothing — skip them (upper bound is static per q-block only via full
  # loop; use masked full loop for grid-static shape, cheap for small S)
  m, l, acc = lax.fori_loop(0, n_kblocks, body, (m0, l0, acc0))
  l = jnp.where(l == 0.0, 1.0, l)
  o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _dense_reference(q, k, v, causal):
  """Dense attention used for the backward pass (differentiable); the
  single source of truth for the math lives in parallel.ring_attention."""
  from tensorflowonspark_tpu.parallel.ring_attention import full_attention
  return full_attention(q, k, v, causal=causal)


def flash_attention(q, k, v, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128, interpret: bool = False):
  """Fused attention. q/k/v: [batch, seq, heads, head_dim].

  Forward runs the Pallas kernel; the backward pass currently recomputes
  through the dense reference (a fused backward kernel is future work —
  training still benefits from the fused forward under remat).
  ``blk_q``/``blk_k`` are clamped to the sequence length; seq must be
  divisible by the resulting blocks.
  """
  # keyword args are normalized here: custom_vjp wants positionals
  return _flash_vjp(q, k, v, causal, blk_q, blk_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_vjp(q, k, v, causal, blk_q, blk_k, interpret):
  return _flash_forward_impl(q, k, v, causal, blk_q, blk_k, interpret)


def _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret):
  out = _flash_forward_impl(q, k, v, causal, blk_q, blk_k, interpret)
  return out, (q, k, v)


def _flash_bwd(causal, blk_q, blk_k, interpret, residuals, g):
  q, k, v = residuals
  _, vjp = jax.vjp(lambda q, k, v: _dense_reference(q, k, v, causal),
                   q, k, v)
  return vjp(g)


_flash_vjp.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "blk_q", "blk_k",
                                             "interpret"))
def _flash_forward_impl(q, k, v, causal, blk_q, blk_k, interpret):
  b, s, h, d = q.shape
  blk_q = min(blk_q, s)
  blk_k = min(blk_k, s)
  assert s % blk_q == 0 and s % blk_k == 0, \
      "seq %d not divisible by blocks (%d, %d)" % (s, blk_q, blk_k)
  scale = 1.0 / (d ** 0.5)

  # [B,S,H,D] -> [B*H, S, D]
  def _fold(x):
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

  qf, kf, vf = _fold(q), _fold(k), _fold(v)

  kernel = functools.partial(_attn_kernel, blk_q=blk_q, blk_k=blk_k,
                             seq_len=s, causal=causal, scale=scale)
  out = pl.pallas_call(
      kernel,
      grid=(b * h, s // blk_q),
      in_specs=[
          pl.BlockSpec((1, blk_q, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
          pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
      ],
      out_specs=pl.BlockSpec((1, blk_q, d), lambda i, j: (i, j, 0)),
      out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
      interpret=interpret,
  )(qf, kf, vf)

  return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
