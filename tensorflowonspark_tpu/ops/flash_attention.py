"""Fused causal attention as Pallas TPU kernels — forward AND backward.

Flash-attention-style: the forward streams over K/V blocks with an online
softmax carried in VMEM scratch, so the [S, S] score matrix never hits HBM
— scores are produced on the MXU, normalized on the VPU, and accumulated in
float32 while inputs stay bfloat16. The forward also emits the per-row
logsumexp, which the backward kernels use to rebuild probabilities
blockwise: dQ comes from a (batch·heads, q-block) grid and dK/dV from a
(batch·heads, k-block) grid, so the backward is fused and HBM-light too
(no dense [S, S] materialization anywhere in training).

``interpret=True`` runs the same kernels on CPU for tests; on TPU the
MXU/VPU path is used. Layout: [batch, seq, heads, head_dim] to match
``parallel.ring_attention``.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _masked_scores(q, k, qi, ki, blk_q, blk_k, causal):
  """Scaled scores for one (q-block, k-block) pair with causal masking."""
  s = q @ k.astype(jnp.float32).T
  if causal:
    q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    k_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    s = jnp.where(k_pos <= q_pos, s, NEG_INF)
  return s


def _attn_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, blk_q: int,
                     blk_k: int, seq_len: int, causal: bool, scale: float):
  qi = pl.program_id(1)
  q = q_ref[0].astype(jnp.float32) * scale          # [blk_q, D]
  n_kblocks = seq_len // blk_k

  def body(ki, carry):
    m, l, acc = carry
    k = lax.dynamic_slice_in_dim(k_ref[0], ki * blk_k, blk_k, 0)
    v = lax.dynamic_slice_in_dim(v_ref[0], ki * blk_k, blk_k, 0)
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe[:, None])
    p = jnp.where(s <= NEG_INF, 0.0, p)
    corr = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[:, None] + p @ v.astype(jnp.float32)
    return m_new, l_new, acc_new

  m0 = jnp.full((blk_q,), NEG_INF, jnp.float32)
  l0 = jnp.zeros((blk_q,), jnp.float32)
  acc0 = jnp.zeros((blk_q, q.shape[-1]), jnp.float32)
  m, l, acc = lax.fori_loop(0, n_kblocks, body, (m0, l0, acc0))

  l_safe = jnp.where(l == 0.0, 1.0, l)
  o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
  # logsumexp of each row's scores (NEG_INF rows stay NEG_INF)
  lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
  lse_ref[0] = lse


def _attn_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, blk_q: int, blk_k: int, seq_len: int,
                        causal: bool, scale: float):
  """dQ for one q-block: dQ = scale · Σ_k [P ⊙ (dO·Vᵀ − Δ)] · K."""
  qi = pl.program_id(1)
  q = q_ref[0].astype(jnp.float32) * scale
  do = do_ref[0].astype(jnp.float32)                # [blk_q, D]
  lse = lse_ref[0]                                  # [blk_q]
  delta = delta_ref[0]                              # [blk_q]
  n_kblocks = seq_len // blk_k

  def body(ki, dq):
    k = lax.dynamic_slice_in_dim(k_ref[0], ki * blk_k, blk_k, 0)
    v = lax.dynamic_slice_in_dim(v_ref[0], ki * blk_k, blk_k, 0)
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal)
    p = jnp.exp(s - lse[:, None])
    p = jnp.where(s <= NEG_INF, 0.0, p)
    dp = do @ v.astype(jnp.float32).T               # [blk_q, blk_k]
    ds = p * (dp - delta[:, None])
    return dq + ds @ k.astype(jnp.float32)

  dq0 = jnp.zeros((blk_q, q.shape[-1]), jnp.float32)
  dq = lax.fori_loop(0, n_kblocks, body, dq0)
  dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, *, blk_q: int, blk_k: int,
                         seq_len: int, causal: bool, scale: float):
  """dK/dV for one k-block: dV = Σ_q Pᵀ·dO; dK = scale · Σ_q dSᵀ·Q."""
  ki = pl.program_id(1)
  k = k_ref[0].astype(jnp.float32)                  # [blk_k, D]
  v = v_ref[0].astype(jnp.float32)
  n_qblocks = seq_len // blk_q

  def body(qi, carry):
    dk, dv = carry
    q = lax.dynamic_slice_in_dim(q_ref[0], qi * blk_q, blk_q, 0) \
        .astype(jnp.float32) * scale
    do = lax.dynamic_slice_in_dim(do_ref[0], qi * blk_q, blk_q, 0) \
        .astype(jnp.float32)
    lse = lax.dynamic_slice_in_dim(lse_ref[0], qi * blk_q, blk_q, 0)
    delta = lax.dynamic_slice_in_dim(delta_ref[0], qi * blk_q, blk_q, 0)
    s = _masked_scores(q, k, qi, ki, blk_q, blk_k, causal)
    p = jnp.exp(s - lse[:, None])
    p = jnp.where(s <= NEG_INF, 0.0, p)
    dv_new = dv + p.T @ do
    dp = do @ v.T
    ds = p * (dp - delta[:, None])
    dk_new = dk + ds.T @ q
    return dk_new, dv_new

  dk0 = jnp.zeros((blk_k, k.shape[-1]), jnp.float32)
  dv0 = jnp.zeros((blk_k, v.shape[-1]), jnp.float32)
  dk, dv = lax.fori_loop(0, n_qblocks, body, (dk0, dv0))
  dk_ref[0] = dk.astype(dk_ref.dtype)   # q was pre-scaled; dk absorbs it
  dv_ref[0] = dv.astype(dv_ref.dtype)


def flash_attention(q, k, v, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128, interpret: bool = False):
  """Fused attention with fused backward. q/k/v: [batch, seq, heads,
  head_dim]; seq must divide by the (clamped) block sizes."""
  # keyword args are normalized here: custom_vjp wants positionals
  return _flash_vjp(q, k, v, causal, blk_q, blk_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_vjp(q, k, v, causal, blk_q, blk_k, interpret):
  out, _ = _flash_forward_impl(q, k, v, causal, blk_q, blk_k, interpret)
  return out


def _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret):
  out, lse = _flash_forward_impl(q, k, v, causal, blk_q, blk_k, interpret)
  return out, (q, k, v, out, lse)


def _flash_bwd(causal, blk_q, blk_k, interpret, residuals, g):
  q, k, v, out, lse = residuals
  return _flash_backward_impl(q, k, v, out, lse, g, causal, blk_q, blk_k,
                              interpret)


_flash_vjp.defvjp(_flash_fwd, _flash_bwd)


def _blocks(s, blk_q, blk_k):
  blk_q = min(blk_q, s)
  blk_k = min(blk_k, s)
  assert s % blk_q == 0 and s % blk_k == 0, \
      "seq %d not divisible by blocks (%d, %d)" % (s, blk_q, blk_k)
  return blk_q, blk_k


def _fold(x, b, s, h, d):
  return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, s, h, d):
  return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.jit, static_argnames=("causal", "blk_q", "blk_k",
                                             "interpret"))
def _flash_forward_impl(q, k, v, causal, blk_q, blk_k, interpret):
  b, s, h, d = q.shape
  blk_q, blk_k = _blocks(s, blk_q, blk_k)
  scale = 1.0 / (d ** 0.5)
  qf, kf, vf = (_fold(x, b, s, h, d) for x in (q, k, v))

  kernel = functools.partial(_attn_fwd_kernel, blk_q=blk_q, blk_k=blk_k,
                             seq_len=s, causal=causal, scale=scale)
  out, lse = pl.pallas_call(
      kernel,
      grid=(b * h, s // blk_q),
      in_specs=[
          pl.BlockSpec((1, blk_q, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
          pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
      ],
      out_specs=[
          pl.BlockSpec((1, blk_q, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, blk_q), lambda i, j: (i, j)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
          jax.ShapeDtypeStruct((b * h, s), jnp.float32),
      ],
      interpret=interpret,
  )(qf, kf, vf)

  return _unfold(out, b, s, h, d), lse


@functools.partial(jax.jit, static_argnames=("causal", "blk_q", "blk_k",
                                             "interpret"))
def _flash_backward_impl(q, k, v, out, lse, g, causal, blk_q, blk_k,
                         interpret):
  b, s, h, d = q.shape
  blk_q, blk_k = _blocks(s, blk_q, blk_k)
  scale = 1.0 / (d ** 0.5)
  qf, kf, vf, of, gf = (_fold(x, b, s, h, d) for x in (q, k, v, out, g))
  # Δ_i = Σ_d dO_id · O_id (softmax-normalization correction term)
  delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)

  common = dict(blk_q=blk_q, blk_k=blk_k, seq_len=s, causal=causal,
                scale=scale)
  full = lambda i, j: (i, 0, 0)       # noqa: E731
  full2 = lambda i, j: (i, 0)         # noqa: E731

  dq = pl.pallas_call(
      functools.partial(_attn_bwd_dq_kernel, **common),
      grid=(b * h, s // blk_q),
      in_specs=[
          pl.BlockSpec((1, blk_q, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, s, d), full),
          pl.BlockSpec((1, s, d), full),
          pl.BlockSpec((1, blk_q, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, blk_q), lambda i, j: (i, j)),
          pl.BlockSpec((1, blk_q), lambda i, j: (i, j)),
      ],
      out_specs=pl.BlockSpec((1, blk_q, d), lambda i, j: (i, j, 0)),
      out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
      interpret=interpret,
  )(qf, kf, vf, gf, lse, delta)

  dk, dv = pl.pallas_call(
      functools.partial(_attn_bwd_dkv_kernel, **common),
      grid=(b * h, s // blk_k),
      in_specs=[
          pl.BlockSpec((1, s, d), full),
          pl.BlockSpec((1, blk_k, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, blk_k, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, s, d), full),
          pl.BlockSpec((1, s), full2),
          pl.BlockSpec((1, s), full2),
      ],
      out_specs=[
          pl.BlockSpec((1, blk_k, d), lambda i, j: (i, j, 0)),
          pl.BlockSpec((1, blk_k, d), lambda i, j: (i, j, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
          jax.ShapeDtypeStruct((b * h, s, d), v.dtype),
      ],
      interpret=interpret,
  )(qf, kf, vf, gf, lse, delta)

  return (_unfold(dq, b, s, h, d), _unfold(dk, b, s, h, d),
          _unfold(dv, b, s, h, d))
