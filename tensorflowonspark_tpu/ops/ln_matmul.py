"""Fused LayerNorm + matmul as a Pallas TPU kernel: ``LN(x) @ W``.

The MFU lever this targets (ROADMAP; round-2 verdict item 7): in a
Transformer block every matmul that consumes a LayerNorm output —
ln1 → QKV projection, ln2 → MLP up-projection — makes XLA materialize the
normalized [rows, H] activation in HBM between two HLOs (LN's reductions
block full fusion into the dot). This kernel computes the row statistics
on the VPU and feeds the normalized block STRAIGHT into the MXU dot from
VMEM: the normalized activation never exists in HBM.

Forward layout: x [..., H] (leading dims flatten to rows), w_ln [H],
W [H, N]. Grid tiles (rows, N); each (i, j) step re-derives the row
stats of its x block — one extra VPU reduction per N-tile, cheaper than
an HBM round-trip of the [rows, H] normalized tensor.

Backward: a custom VJP recomputes ``xhat`` in plain XLA (two matmuls +
the standard two-reduction LN backward) — the backward is matmul-bound
and XLA already schedules those well; the fusion win is the forward.
float32 statistics over bfloat16 activations, matching ops.layer_norm.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tensorflowonspark_tpu.ops.layer_norm import _pick_block, _stats


def _ln_matmul_kernel(x_ref, wln_ref, w_ref, o_ref, *, eps: float):
  x = x_ref[...].astype(jnp.float32)                 # [blk_r, H]
  mu, rstd = _stats(x, eps)
  xn = (x - mu) * rstd * wln_ref[...].astype(jnp.float32)
  w = w_ref[...]                                     # [H, blk_n]
  acc = jax.lax.dot_general(
      xn.astype(w.dtype), w, (((1,), (0,)), ((), ())),
      preferred_element_type=jnp.float32)
  o_ref[...] = acc.astype(o_ref.dtype)


def _pick_col_block(n: int, blk_cols: int) -> int:
  """Largest LANE-ALIGNED divisor of ``n`` <= blk_cols, or ``n`` itself
  when none exists. Mosaic accepts a last-dim block only if it is a
  multiple of 128 or the whole dimension — a bare largest-divisor snap
  (1280 cols @ blk 512 → 320) fails real TPU lowering; caught by the
  deviceless gate on the GQA fused-QKV sweep config (its h+2·hk=20-head
  projection has N=1280)."""
  blk = min(blk_cols, n)
  for b in range(blk - blk % 128, 0, -128):
    if n % b == 0:
      return b
  # requested block under the 128-lane floor (or no aligned divisor
  # beneath it): snap UP to the smallest aligned divisor before falling
  # back to one whole-dimension block
  for b in range(128, n, 128):
    if n % b == 0:
      return b
  return n


def effective_blocks(rows: int, h: int, n: int, blk_rows: int,
                     blk_cols: int):
  """The (row, col) block pair the kernel will ACTUALLY run after
  divisor fitting — the forward uses this, and tools/tpu_validate's
  block sweep dedups/labels through it so tuning artifacts can never
  name a configuration the kernel would silently snap away from."""
  return _pick_block(rows, blk_rows, h), _pick_col_block(n, blk_cols)


def _ln_matmul_fwd(x, w_ln, W, eps, blk_rows, blk_cols, interpret):
  shape = x.shape
  h = shape[-1]
  n = W.shape[-1]
  rows = 1
  for s in shape[:-1]:
    rows *= s
  xf = x.reshape(rows, h)
  wln2 = w_ln.reshape(1, h)
  blk_r, blk_n = effective_blocks(rows, h, n, blk_rows, blk_cols)

  out = pl.pallas_call(
      functools.partial(_ln_matmul_kernel, eps=eps),
      grid=(rows // blk_r, n // blk_n),
      in_specs=[
          pl.BlockSpec((blk_r, h), lambda i, j: (i, 0)),
          pl.BlockSpec((1, h), lambda i, j: (0, 0)),
          pl.BlockSpec((h, blk_n), lambda i, j: (0, j)),
      ],
      out_specs=pl.BlockSpec((blk_r, blk_n), lambda i, j: (i, j)),
      out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
      interpret=interpret,
  )(xf, wln2, W)
  return out.reshape(shape[:-1] + (n,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ln_matmul_vjp(x, w_ln, W, eps, blk_rows, blk_cols, interpret):
  return _ln_matmul_fwd(x, w_ln, W, eps, blk_rows, blk_cols, interpret)


def _fwd_rule(x, w_ln, W, eps, blk_rows, blk_cols, interpret):
  return (_ln_matmul_fwd(x, w_ln, W, eps, blk_rows, blk_cols, interpret),
          (x, w_ln, W))


def _bwd_rule(eps, blk_rows, blk_cols, interpret, res, g):
  x, w_ln, W = res
  shape = x.shape
  h = shape[-1]
  xf = x.reshape(-1, h).astype(jnp.float32)
  gf = g.reshape(-1, W.shape[-1])
  mu, rstd = _stats(xf, eps)
  xhat = (xf - mu) * rstd                            # [R, H] f32
  y = (xhat * w_ln.astype(jnp.float32)).astype(x.dtype)
  # dW = LN(x)^T @ g ; gy = g @ W^T flows into the LN backward
  dW = jax.lax.dot_general(y, gf.astype(x.dtype), (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
  gy = jax.lax.dot_general(gf.astype(x.dtype), W, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)
  dw_ln = jnp.sum(gy * xhat, axis=0)
  dy = gy * w_ln.astype(jnp.float32)
  m1 = jnp.mean(dy, axis=-1, keepdims=True)
  m2 = jnp.mean(dy * xhat, axis=-1, keepdims=True)
  dx = rstd * (dy - m1 - xhat * m2)
  return (dx.reshape(shape).astype(x.dtype), dw_ln.astype(w_ln.dtype),
          dW.astype(W.dtype))


_ln_matmul_vjp.defvjp(_fwd_rule, _bwd_rule)


def ln_matmul(x, w_ln, W, eps: float = 1e-6, blk_rows: int = 128,
              blk_cols: int = 512, interpret: bool = False):
  """``layer_norm(x, w_ln) @ W`` with the normalized activation never
  leaving VMEM. x: [..., H]; w_ln: [H]; W: [H, N] → [..., N].
  Differentiable (custom VJP; backward recomputes the norm in XLA).
  """
  return _ln_matmul_vjp(x, w_ln, W, eps, blk_rows, blk_cols, interpret)


def ln_matmul_sharded(x, w_ln, W, mesh, eps: float = 1e-6,
                      blk_rows: int = 128, blk_cols: int = 512,
                      interpret: bool = False, batch_axes=None):
  """Fused LN+matmul applied per-shard through shard_map.

  The sharded-model analog of :func:`ln_matmul`, following the
  ``ops.layer_norm_sharded`` precedent: an unpartitioned ``pallas_call``
  over GSPMD-sharded activations would force XLA to gather them, so the
  kernel maps over shards instead (round-3 verdict item 4 — without this
  the flagship multi-chip training path got no LN→matmul fusion).

  x: [batch, seq, H] with batch sharded over data(+fsdp) and seq
  optionally over the sequence axis; w_ln: [H] replicated; W: [H, N]
  with N split over the tensor axis when divisible (the QKV-heads /
  MLP-up layouts), replicated otherwise. H must be unsharded — the norm
  reduces over it and each device's dot contracts it fully, so the
  forward needs no collectives at all. Gradients: shard_map's transpose
  psums dW / dw_ln over the row (data/sequence) axes, matching the
  dense AD (asserted in tests/test_ops.py).
  """
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map
  from jax.sharding import PartitionSpec as P
  from tensorflowonspark_tpu.parallel import mesh as mesh_lib

  if batch_axes is None:
    batch_axes = mesh_lib.data_axes(mesh)
  seq_axis = mesh_lib.AXIS_SEQUENCE \
      if mesh_lib.AXIS_SEQUENCE in mesh.axis_names else None
  tensor_axis = mesh_lib.AXIS_TENSOR \
      if mesh_lib.AXIS_TENSOR in mesh.axis_names else None
  if tensor_axis and W.shape[-1] % mesh.shape[tensor_axis] != 0:
    tensor_axis = None   # indivisible column count: keep W replicated
  xspec = P(batch_axes or None, seq_axis, None)
  fn = shard_map(
      lambda xs, wl, ws: _ln_matmul_vjp(xs, wl, ws, eps, blk_rows,
                                        blk_cols, interpret),
      mesh=mesh, in_specs=(xspec, P(None), P(None, tensor_axis)),
      out_specs=P(batch_axes or None, seq_axis, tensor_axis),
      check_vma=False)
  return fn(x, w_ln, W)
