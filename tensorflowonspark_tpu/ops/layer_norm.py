"""Fused LayerNorm as a Pallas TPU kernel.

One VMEM pass computes mean/variance on the VPU and applies the normalize
+ scale in place — no separate mean/var/normalize HLOs materializing
intermediates in HBM for long sequences. float32 statistics over bfloat16
activations; custom VJP with a fused backward (the standard two-reduction
formulation) that RECOMPUTES the row statistics from the residual ``x``
instead of storing them: on real TPUs, 1-D blocked operands (stats of
shape [rows]) fail Mosaic's layout verification against XLA's 1-D T(1024)
tiling, and recomputing one VPU reduction over data already resident in
VMEM is cheaper than the extra HBM round-trip anyway. All operands are
kept 2-D and lane-aligned.

Layout: [..., hidden]; the leading dims are flattened to rows and tiled
over the grid.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _stats(x, eps):
  """Row mean and reciprocal stddev, keepdims ([blk, 1] columns)."""
  mu = jnp.mean(x, axis=-1, keepdims=True)
  xc = x - mu
  var = jnp.mean(xc * xc, axis=-1, keepdims=True)
  return mu, jax.lax.rsqrt(var + eps)


def _ln_fwd_kernel(x_ref, w_ref, o_ref, *, eps: float):
  x = x_ref[...].astype(jnp.float32)                # [blk, H]
  mu, rstd = _stats(x, eps)
  y = (x - mu) * rstd * w_ref[...].astype(jnp.float32)
  o_ref[...] = y.astype(o_ref.dtype)


def _ln_bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, *, eps: float):
  x = x_ref[...].astype(jnp.float32)
  w = w_ref[...].astype(jnp.float32)                # [1, H]
  g = g_ref[...].astype(jnp.float32)
  mu, rstd = _stats(x, eps)
  xhat = (x - mu) * rstd
  dy = g * w
  # dx = rstd * (dy - mean(dy) - xhat * mean(dy * xhat))
  m1 = jnp.mean(dy, axis=-1, keepdims=True)
  m2 = jnp.mean(dy * xhat, axis=-1, keepdims=True)
  dx = rstd * (dy - m1 - xhat * m2)
  dx_ref[...] = dx.astype(dx_ref.dtype)
  # dw accumulates across the (sequential) grid into one [1, H] output —
  # Mosaic rejects a per-block [n_blocks, H] partial sliced (1, H), so the
  # reduction happens in-kernel instead of outside
  rowsum = jnp.sum(g * xhat, axis=0, keepdims=True)

  @pl.when(pl.program_id(0) == 0)
  def _init():
    dw_ref[...] = rowsum

  @pl.when(pl.program_id(0) != 0)
  def _acc():
    dw_ref[...] += rowsum


def layer_norm(x, weight, eps: float = 1e-6, blk_rows: int = 128,
               interpret: bool = False):
  """Fused LayerNorm (no bias): ``(x - mean) * rsqrt(var + eps) * weight``.

  x: [..., hidden]; weight: [hidden]. Differentiable (fused backward).
  """
  return _ln_vjp(x, weight, eps, blk_rows, interpret)


def layer_norm_sharded(x, weight, mesh, eps: float = 1e-6,
                       blk_rows: int = 128, interpret: bool = False,
                       batch_axes=None):
  """Fused LayerNorm applied per-shard through shard_map.

  For activations living inside a GSPMD-partitioned model: an
  unpartitioned ``pallas_call`` on sharded activations would force XLA to
  gather them; mapping the kernel over shards keeps each device's rows
  local (the norm reduces only over ``hidden``, which must be unsharded).

  x: [batch, seq, hidden] with batch sharded over the data(+fsdp) axes and
  seq optionally over the sequence axis; weight replicated.
  """
  from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map
  from jax.sharding import PartitionSpec as P
  from tensorflowonspark_tpu.parallel import mesh as mesh_lib

  if batch_axes is None:
    batch_axes = mesh_lib.data_axes(mesh)
  seq_axis = mesh_lib.AXIS_SEQUENCE \
      if mesh_lib.AXIS_SEQUENCE in mesh.axis_names else None
  spec = P(batch_axes or None, seq_axis, None)
  fn = shard_map(
      lambda xs, w: layer_norm(xs, w, eps, blk_rows, interpret),
      mesh=mesh, in_specs=(spec, P(None)), out_specs=spec, check_vma=False)
  return fn(x, weight)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ln_vjp(x, weight, eps, blk_rows, interpret):
  return _ln_fwd(x, weight, eps, blk_rows, interpret)


def _ln_fwd_rule(x, weight, eps, blk_rows, interpret):
  y = _ln_fwd(x, weight, eps, blk_rows, interpret)
  return y, (x, weight)


def _pick_block(rows: int, blk_rows: int, h: int, itemsize: int = 0) -> int:
  """Largest SUBLANE-ALIGNED block <= blk_rows that divides the row count
  (a multiple of 8 — Mosaic accepts a second-minor block dim only if it
  is 8-aligned or the whole dimension; when no aligned divisor exists,
  e.g. odd row counts, fall back to one full-dimension block).

  With ``itemsize`` set (the BACKWARD path), the block is additionally
  capped so one [blk, H] input block stays <= 1 MiB: the f32 backward at
  H=4096 with 128-row blocks crashes the real-TPU compile helper, while
  the forward at the same shape, the bf16 backward at blk=128, and the
  f32 backward at blk=64 all compile fine — so the cap keys off the
  actual element footprint and is not applied to the forward.

  The full-dimension fallback (rows not a multiple of 8, e.g. 4100) can
  exceed the cap — deliberately: a small unaligned divisor would pass
  interpret mode and fail real Mosaic lowering (the round-2 trap), so
  the ONLY Mosaic-valid block for such shapes is the whole dimension,
  VMEM cost and all. Pad the row count to a multiple of 8 upstream if
  that footprint is too large."""
  blk = min(blk_rows, rows)
  if itemsize:
    blk = min(blk, max(8, (1 << 20) // (h * itemsize)))
  for b in range(blk - blk % 8, 0, -8):
    if rows % b == 0:
      return b
  # under the 8-sublane floor: snap UP to the smallest aligned divisor
  # before resorting to one whole-dimension block
  for b in range(8, rows, 8):
    if rows % b == 0:
      return b
  return rows


def _ln_fwd(x, weight, eps, blk_rows, interpret):
  shape = x.shape
  h = shape[-1]
  rows = 1
  for s in shape[:-1]:
    rows *= s
  xf = x.reshape(rows, h)
  w2 = weight.reshape(1, h)
  blk = _pick_block(rows, blk_rows, h)

  y = pl.pallas_call(
      functools.partial(_ln_fwd_kernel, eps=eps),
      grid=(rows // blk,),
      in_specs=[
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
          pl.BlockSpec((1, h), lambda i: (0, 0)),
      ],
      out_specs=pl.BlockSpec((blk, h), lambda i: (i, 0)),
      out_shape=jax.ShapeDtypeStruct((rows, h), x.dtype),
      interpret=interpret,
  )(xf, w2)
  return y.reshape(shape)


def _ln_bwd_rule(eps, blk_rows, interpret, residuals, g):
  x, weight = residuals
  shape = x.shape
  h = shape[-1]
  rows = 1
  for s in shape[:-1]:
    rows *= s
  xf = x.reshape(rows, h)
  gf = g.reshape(rows, h)
  w2 = weight.reshape(1, h)
  blk = _pick_block(rows, blk_rows, h, jnp.dtype(x.dtype).itemsize)

  dx, dw_partial = pl.pallas_call(
      functools.partial(_ln_bwd_kernel, eps=eps),
      grid=(rows // blk,),
      in_specs=[
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
          pl.BlockSpec((1, h), lambda i: (0, 0)),
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
      ],
      out_specs=[
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
          pl.BlockSpec((1, h), lambda i: (0, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((rows, h), x.dtype),
          jax.ShapeDtypeStruct((1, h), jnp.float32),
      ],
      interpret=interpret,
  )(xf, w2, gf)

  dw = dw_partial[0].astype(weight.dtype)
  return dx.reshape(shape), dw


_ln_vjp.defvjp(_ln_fwd_rule, _ln_bwd_rule)
