"""Fused LayerNorm as a Pallas TPU kernel.

One VMEM pass computes mean/variance on the VPU and applies the normalize
+ scale in place — no separate mean/var/normalize HLOs materializing
intermediates in HBM for long sequences. float32 statistics over bfloat16
activations; custom VJP with a fused backward (the standard two-reduction
formulation).

Layout: [..., hidden]; the leading dims are flattened to rows and tiled
over the grid.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _ln_fwd_kernel(x_ref, w_ref, o_ref, mu_ref, rstd_ref, *, eps: float):
  x = x_ref[...].astype(jnp.float32)                # [blk, H]
  mu = jnp.mean(x, axis=-1)
  xc = x - mu[:, None]
  var = jnp.mean(xc * xc, axis=-1)
  rstd = jax.lax.rsqrt(var + eps)
  y = xc * rstd[:, None] * w_ref[...].astype(jnp.float32)[None, :]
  o_ref[...] = y.astype(o_ref.dtype)
  mu_ref[...] = mu
  rstd_ref[...] = rstd


def _ln_bwd_kernel(x_ref, w_ref, mu_ref, rstd_ref, g_ref, dx_ref, dwp_ref):
  x = x_ref[...].astype(jnp.float32)
  w = w_ref[...].astype(jnp.float32)[None, :]
  g = g_ref[...].astype(jnp.float32)
  mu = mu_ref[...]
  rstd = rstd_ref[...]
  xhat = (x - mu[:, None]) * rstd[:, None]
  dy = g * w
  # dx = rstd * (dy - mean(dy) - xhat * mean(dy * xhat))
  m1 = jnp.mean(dy, axis=-1, keepdims=True)
  m2 = jnp.mean(dy * xhat, axis=-1, keepdims=True)
  dx = rstd[:, None] * (dy - m1 - xhat * m2)
  dx_ref[...] = dx.astype(dx_ref.dtype)
  # per-block partial of dw (summed over rows); reduced outside
  dwp_ref[...] = jnp.sum(g * xhat, axis=0)[None, :]


def layer_norm(x, weight, eps: float = 1e-6, blk_rows: int = 128,
               interpret: bool = False):
  """Fused LayerNorm (no bias): ``(x - mean) * rsqrt(var + eps) * weight``.

  x: [..., hidden]; weight: [hidden]. Differentiable (fused backward).
  """
  return _ln_vjp(x, weight, eps, blk_rows, interpret)


def layer_norm_sharded(x, weight, mesh, eps: float = 1e-6,
                       blk_rows: int = 128, interpret: bool = False,
                       batch_axes=None):
  """Fused LayerNorm applied per-shard through shard_map.

  For activations living inside a GSPMD-partitioned model: an
  unpartitioned ``pallas_call`` on sharded activations would force XLA to
  gather them; mapping the kernel over shards keeps each device's rows
  local (the norm reduces only over ``hidden``, which must be unsharded).

  x: [batch, seq, hidden] with batch sharded over the data(+fsdp) axes and
  seq optionally over the sequence axis; weight replicated.
  """
  from jax import shard_map
  from jax.sharding import PartitionSpec as P
  from tensorflowonspark_tpu.parallel import mesh as mesh_lib

  if batch_axes is None:
    batch_axes = mesh_lib.data_axes(mesh)
  seq_axis = mesh_lib.AXIS_SEQUENCE \
      if mesh_lib.AXIS_SEQUENCE in mesh.axis_names else None
  spec = P(batch_axes or None, seq_axis, None)
  fn = shard_map(
      lambda xs, w: layer_norm(xs, w, eps, blk_rows, interpret),
      mesh=mesh, in_specs=(spec, P(None)), out_specs=spec, check_vma=False)
  return fn(x, weight)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ln_vjp(x, weight, eps, blk_rows, interpret):
  return _ln_fwd(x, weight, eps, blk_rows, interpret)[0]


def _ln_fwd_rule(x, weight, eps, blk_rows, interpret):
  y, mu, rstd = _ln_fwd(x, weight, eps, blk_rows, interpret)
  return y, (x, weight, mu, rstd)


def _pick_block(rows: int, blk_rows: int) -> int:
  """Largest block <= blk_rows that divides the row count (always >= 1),
  so any shape works without padding or uncovered rows."""
  blk = min(blk_rows, rows)
  while rows % blk != 0:
    blk -= 1
  return blk


def _ln_fwd(x, weight, eps, blk_rows, interpret):
  shape = x.shape
  h = shape[-1]
  rows = 1
  for s in shape[:-1]:
    rows *= s
  xf = x.reshape(rows, h)
  blk = _pick_block(rows, blk_rows)

  y, mu, rstd = pl.pallas_call(
      functools.partial(_ln_fwd_kernel, eps=eps),
      grid=(rows // blk,),
      in_specs=[
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
          pl.BlockSpec((h,), lambda i: (0,)),
      ],
      out_specs=[
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
          pl.BlockSpec((blk,), lambda i: (i,)),
          pl.BlockSpec((blk,), lambda i: (i,)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((rows, h), x.dtype),
          jax.ShapeDtypeStruct((rows,), jnp.float32),
          jax.ShapeDtypeStruct((rows,), jnp.float32),
      ],
      interpret=interpret,
  )(xf, weight)
  return y.reshape(shape), mu, rstd


def _ln_bwd_rule(eps, blk_rows, interpret, residuals, g):
  x, weight, mu, rstd = residuals
  shape = x.shape
  h = shape[-1]
  rows = mu.shape[0]
  xf = x.reshape(rows, h)
  gf = g.reshape(rows, h)
  blk = _pick_block(rows, blk_rows)

  dx, dw_partial = pl.pallas_call(
      _ln_bwd_kernel,
      grid=(rows // blk,),
      in_specs=[
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
          pl.BlockSpec((h,), lambda i: (0,)),
          pl.BlockSpec((blk,), lambda i: (i,)),
          pl.BlockSpec((blk,), lambda i: (i,)),
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
      ],
      out_specs=[
          pl.BlockSpec((blk, h), lambda i: (i, 0)),
          pl.BlockSpec((1, h), lambda i: (i, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((rows, h), x.dtype),
          jax.ShapeDtypeStruct((rows // blk, h), jnp.float32),
      ],
      interpret=interpret,
  )(xf, weight, mu, rstd, gf)

  dw = jnp.sum(dw_partial, axis=0).astype(weight.dtype)
  return dx.reshape(shape), dw


_ln_vjp.defvjp(_ln_fwd_rule, _ln_bwd_rule)
