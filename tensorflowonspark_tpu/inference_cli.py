"""Batch-inference CLI: TFRecords in → predictions out, no cluster setup.

Replaces the reference's JVM-only inference path
(/root/reference/src/main/scala/.../Inference.scala:17-80: a spark-submit
CLI with --export_dir/--input/--schema_hint/--input_mapping/
--output_mapping/--output) with a ``python -m tensorflowonspark_tpu.inference_cli``
entry point over the LocalEngine (or Spark when available).

Example:
  python -m tensorflowonspark_tpu.inference_cli \
      --export_dir /models/m1 \
      --input /data/part-*.tfrecord \
      --schema_hint 'struct<x1:float,x2:float>' \
      --input_mapping '{"x1":"x1","x2":"x2"}' \
      --output_mapping '{"y":"pred"}' \
      --output /tmp/preds.jsonl
"""

import argparse
import json
import logging
import os
import sys

logger = logging.getLogger(__name__)


def _validate_output_path(path: str) -> None:
  """Fail fast on an unwritable --output destination.

  Predictions stream to the output file only AFTER the engine ran the
  whole transform — a bad path must be rejected up front, not as a
  traceback after minutes of inference.
  """
  parent = os.path.dirname(os.path.abspath(path))
  if not os.path.isdir(parent):
    raise SystemExit(
        "--output %s: parent directory %s does not exist — create it "
        "first (predictions are written only after inference completes, "
        "so this would fail at the very end)" % (path, parent))
  if not os.access(parent, os.W_OK):
    raise SystemExit("--output %s: parent directory %s is not writable"
                     % (path, parent))
  if os.path.isdir(path):
    raise SystemExit("--output %s is a directory; pass a file path"
                     % path)


def build_parser() -> argparse.ArgumentParser:
  p = argparse.ArgumentParser(
      prog="tensorflowonspark_tpu.inference_cli",
      description="Batch inference over TFRecord files (parity: the "
                  "reference's Scala Inference CLI).")
  p.add_argument("--export_dir", required=True,
                 help="model bundle directory (pipeline.export_bundle)")
  p.add_argument("--input", required=True,
                 help="TFRecord file, directory, or glob")
  p.add_argument("--output", required=True,
                 help="output path for JSONL predictions")
  p.add_argument("--schema_hint", default=None,
                 help="struct<name:type,...> schema for the input records")
  p.add_argument("--input_mapping", default=None,
                 help="JSON {column: input_tensor}")
  p.add_argument("--output_mapping", default=None,
                 help="JSON {output_tensor: column}")
  p.add_argument("--batch_size", type=int, default=128)
  p.add_argument("--num_executors", type=int, default=1)
  p.add_argument("--engine", choices=["local", "spark"], default="local")
  p.add_argument("--verbose", action="store_true")
  return p


def main(argv=None) -> int:
  args = build_parser().parse_args(argv)
  if args.verbose:
    logging.basicConfig(level=logging.INFO)
  _validate_output_path(args.output)

  from tensorflowonspark_tpu.data import dfutil
  from tensorflowonspark_tpu.data.schema import parse_schema
  from tensorflowonspark_tpu.engine import get_engine
  from tensorflowonspark_tpu.pipeline import TFModel

  schema = parse_schema(args.schema_hint) if args.schema_hint else None
  partitions, schema = dfutil.load_tfrecords(
      args.input, schema=schema, num_partitions=args.num_executors)
  logger.info("loaded %d partition(s), schema %s", len(partitions), schema)

  input_mapping = json.loads(args.input_mapping) if args.input_mapping \
      else {name: name for name in schema.names()}
  output_mapping = json.loads(args.output_mapping) if args.output_mapping \
      else {}

  # order row columns by sorted(input_mapping) as the estimator does
  col_index = {n: i for i, n in enumerate(schema.names())}
  ordered_cols = sorted(input_mapping)
  missing = [c for c in ordered_cols if c not in col_index]
  if missing:
    raise SystemExit("input_mapping columns %r not in schema %s"
                     % (missing, schema))
  projected = [[tuple(row[col_index[c]] for c in ordered_cols)
                for row in part] for part in partitions]

  if output_mapping:
    out_names = [output_mapping[t] for t in sorted(output_mapping)]
  else:
    # transformSchema parity: without an explicit mapping the bundle's
    # recorded signature names the output columns (TFModel.scala:294-311);
    # the shared helper keeps this in lockstep with TFModel.transform's
    # value order
    from tensorflowonspark_tpu.pipeline import signature_output_names
    out_names = signature_output_names(args.export_dir) or ["prediction"]
  engine = get_engine(args.engine, num_executors=args.num_executors)
  count = 0
  try:
    model = TFModel({"export_dir": args.export_dir,
                     "input_mapping": input_mapping,
                     "output_mapping": output_mapping,
                     "batch_size": args.batch_size})
    # collect=False: predictions stream to the output file one window of
    # partitions at a time — the driver never holds the full result set
    stream = model.transform(engine, projected, collect=False)
    if hasattr(stream, "toLocalIterator"):   # Spark hands back a lazy RDD
      stream = stream.toLocalIterator()
    with open(args.output, "w") as f:
      for row in stream:
        values = row if isinstance(row, tuple) else (row,)
        f.write(json.dumps(dict(zip(out_names, values))) + "\n")
        count += 1
  finally:
    engine.stop()

  logger.info("wrote %d prediction(s) to %s", count, args.output)
  print("wrote %d predictions to %s" % (count, args.output))
  return 0


if __name__ == "__main__":
  sys.exit(main())
