"""Deprecated shim: the profiler moved into the observability plane.

``utils/profiler.py`` grew into the measurement plane's training-loop
seam (StepTimer feeds the metrics registry) and now lives at
``tensorflowonspark_tpu.obs.profiler``. This module re-exports the full
old surface so existing imports keep working; new code should import
from ``obs.profiler`` (or use the higher-level ``obs`` plane directly).
"""

import warnings

from tensorflowonspark_tpu.obs.profiler import (  # noqa: F401
    PEAK_BF16_FLOPS,
    StepTimer,
    annotate,
    device_memory_stats,
    mfu,
    resolve_chip_generation,
    start_server,
    trace,
    transformer_flops_per_token,
)

warnings.warn(
    "tensorflowonspark_tpu.utils.profiler moved to "
    "tensorflowonspark_tpu.obs.profiler; this shim will be removed",
    DeprecationWarning, stacklevel=2)
