"""Profiling/tracing helpers: the JAX-native TensorBoard story.

The reference's only tracing facility was launching TensorBoard as a
subprocess on chief/worker:0 (reference TFSparkNode.py:292-329 — that part
lives in node.py here). This module adds what TPU users actually profile
with: the JAX profiler — a programmatic trace context writing XProf/
perfetto data TensorBoard can render, and an on-demand capture server.
"""

import contextlib
import logging
import os

logger = logging.getLogger(__name__)

_server = None


def start_server(port: int = 9999):
  """Start the JAX profiler capture server (connect with TensorBoard's
  profile tab or `xprof`); idempotent per process."""
  global _server
  if _server is None:
    import jax
    _server = jax.profiler.start_server(port)
    logger.info("JAX profiler server listening on port %d", port)
  return _server


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2):
  """Trace a region into ``log_dir`` (viewable in TensorBoard).

  Usage::

      with profiler.trace("/tmp/tb"):
          state, loss = train_step(state, batch)
          jax.block_until_ready(loss)
  """
  import jax
  os.makedirs(log_dir, exist_ok=True)
  with jax.profiler.trace(log_dir):
    yield
  logger.info("profile trace written to %s", log_dir)


def annotate(name: str):
  """Named region annotation for traces (shows up on the timeline)."""
  import jax
  return jax.profiler.TraceAnnotation(name)
