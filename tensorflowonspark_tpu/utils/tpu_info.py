"""TPU chip/host discovery and per-worker chip allocation.

This is the TPU-native replacement for the reference's ``gpu_info.py``
(/root/reference/tensorflowonspark/gpu_info.py), which discovered and allocated
GPUs by parsing ``nvidia-smi`` and exporting ``CUDA_VISIBLE_DEVICES``. On TPU
there is no ``nvidia-smi``; discovery comes from (in priority order):

1. libtpu/Cloud-TPU environment variables (``TPU_ACCELERATOR_TYPE``,
   ``TPU_WORKER_HOSTNAMES``, ``TPU_PROCESS_BOUNDS``, ...), which exist on TPU
   VMs *before* any runtime is initialized, and
2. ``jax.devices()``, when JAX is importable and initializing it is acceptable
   (initializing grabs the TPU — so the orchestration layer prefers (1)).

Allocation: where the reference exported ``CUDA_VISIBLE_DEVICES`` for a
worker's GPU share (gpu_info.py:80-91), we export ``TPU_VISIBLE_CHIPS`` plus
the ``TPU_PROCESS_*`` multi-process coordinates so several workers can share
one TPU host, each owning a disjoint set of chips.

All discovery functions are pure / env-driven so they can be unit-tested with
``unittest.mock`` exactly like the reference's GPU-policy matrix
(reference tests/test_TFSparkNode.py:49-190).
"""

import logging
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

#: env registry (tools.analyze TOS008) — chip-allocation knobs consumed
#: across node.py / pipeline.py / utils.hostinfo:
#: skip all chip claiming (CPU test runs against fake topologies)
ENV_TEST_MODE = "TOS_TPU_TEST_MODE"
#: sentinel exported once a process has claimed its chip share, so a later
#: task on the same executor process does not double-claim
ENV_CHIP_ENV_APPLIED = "TOS_CHIP_ENV_APPLIED"

# Accelerator type → (chips/host, name_cores/chip, jax_devices/chip).
# The accelerator-type suffix counts TensorCores on v2/v3/v4/v5p (2 cores per
# chip) and chips on v5e/v6e (1 core per chip). v4+ chips are megacore: JAX
# exposes 1 device per chip even where the *name* counts 2 cores.
_ACCEL_INFO = {
    "v2": (4, 2, 2),
    "v3": (4, 2, 2),
    "v4": (4, 2, 1),
    "v5litepod": (8, 1, 1),
    "v5e": (8, 1, 1),
    "v5p": (4, 2, 1),
    "v6e": (8, 1, 1),
}

MAX_CHIPS_PER_HOST = 8


@dataclass
class TPUTopology:
  """Static description of the TPU slice this job runs on."""
  accelerator_type: str = "unknown"   # e.g. "v5litepod-16"
  generation: str = "unknown"         # e.g. "v5litepod"
  num_chips: int = 0                  # total chips in the slice
  chips_per_host: int = 0
  cores_per_chip: int = 1             # TensorCores per chip (naming units)
  devices_per_chip: int = 1           # JAX devices per chip (1 on megacore v4+)
  num_hosts: int = 0
  hostnames: List[str] = field(default_factory=list)

  @property
  def num_devices(self) -> int:
    """Number of JAX devices the slice exposes."""
    return self.num_chips * self.devices_per_chip


def parse_accelerator_type(accel: str) -> TPUTopology:
  """Parse a Cloud-TPU accelerator type string like ``v5litepod-16``."""
  m = re.match(r"(v\d+[a-z]*)-(\d+)", accel)
  if not m:
    raise ValueError("unrecognized TPU accelerator type: {!r}".format(accel))
  gen, size = m.group(1), int(m.group(2))
  chips_per_host, cores_per_chip, devices_per_chip = _ACCEL_INFO.get(
      gen, (4, 1, 1))
  num_chips = max(1, size // cores_per_chip)
  num_hosts = max(1, num_chips // chips_per_host)
  if num_chips < chips_per_host:
    chips_per_host = num_chips
  return TPUTopology(
      accelerator_type=accel, generation=gen, num_chips=num_chips,
      chips_per_host=chips_per_host, cores_per_chip=cores_per_chip,
      devices_per_chip=devices_per_chip, num_hosts=num_hosts,
      hostnames=[])


def from_env(environ: Optional[Dict[str, str]] = None) -> Optional[TPUTopology]:
  """Discover topology from Cloud-TPU VM env vars without touching the device.

  Returns None when the env carries no TPU markers (e.g. CPU CI hosts).
  """
  env = os.environ if environ is None else environ
  accel = env.get("TPU_ACCELERATOR_TYPE")
  if not accel:
    return None
  try:
    topo = parse_accelerator_type(accel)
  except ValueError:
    logger.warning("unparseable TPU_ACCELERATOR_TYPE=%r", accel)
    return None
  hosts = env.get("TPU_WORKER_HOSTNAMES", "")
  if hosts:
    topo.hostnames = [h.strip() for h in hosts.split(",") if h.strip()]
    topo.num_hosts = len(topo.hostnames)
  return topo


def from_jax() -> Optional[TPUTopology]:
  """Discover topology by initializing JAX (grabs the TPU — use sparingly)."""
  try:
    import jax
    devices = jax.devices()
  except Exception as e:  # noqa: BLE001 - any backend failure means "no TPU"
    logger.debug("jax device discovery failed: %s", e)
    return None
  tpus = [d for d in devices if d.platform == "tpu" or "TPU" in str(d.device_kind)]
  if not tpus:
    return None
  kind = str(tpus[0].device_kind)
  hosts = len({d.process_index for d in tpus})
  return TPUTopology(
      accelerator_type=kind, generation=kind, num_chips=len(tpus),
      chips_per_host=max(1, len(tpus) // hosts), cores_per_chip=1,
      num_hosts=hosts)


def get_topology(environ: Optional[Dict[str, str]] = None,
                 allow_jax_init: bool = False) -> Optional[TPUTopology]:
  """Best available topology: env first, optionally JAX as fallback."""
  topo = from_env(environ)
  if topo is None and allow_jax_init:
    topo = from_jax()
  return topo


def is_tpu_available(environ: Optional[Dict[str, str]] = None) -> bool:
  """True when this host can see TPU hardware (parity: gpu_info.is_gpu_available)."""
  return get_topology(environ) is not None or os.path.exists("/dev/accel0") \
      or os.path.exists("/dev/vfio/0")


# physical chip grid of one host, by generation: libtpu requires per-process
# and process bounds that TILE this grid (x, y products, z always 1 per host)
_HOST_CHIP_GRID = {
    "v2": (2, 2), "v3": (2, 2), "v4": (2, 2), "v5p": (2, 2),
    "v5litepod": (2, 4), "v5e": (2, 4), "v6e": (2, 4),
}


def _fit_grid(count: int, bounds):
  """Largest-x ``(x, y)`` with ``x*y == count`` that tiles ``bounds``
  (x | bounds_x and bounds_y % y == 0), or None when no arrangement fits."""
  bx, by = bounds
  for x in range(bx, 0, -1):
    if bx % x or count % x:
      continue
    y = count // x
    if y <= by and by % y == 0:
      return (x, y)
  return None


def chip_env_for_worker(num_chips: int, worker_index: int,
                        workers_per_host: int,
                        base_port: int = 8476,
                        host: str = "localhost",
                        generation: Optional[str] = None) -> Dict[str, str]:
  """Env vars granting ``worker_index`` a disjoint set of chips on this host.

  TPU analog of the reference's deterministic by-worker-index GPU placement
  (gpu_info.py:80-91): worker *i* of *n* on a host with ``n*num_chips`` chips
  gets chips ``[i*num_chips, (i+1)*num_chips)``. Exports the libtpu
  multi-process coordination variables so each worker process initializes only
  its share.

  The exported bounds tile the host's physical chip grid for ``generation``
  (2x4 on v5e/v6e, 2x2 on v4/v5p — libtpu rejects bounds that don't tile the
  topology): e.g. 2 workers x 4 chips on v5e gets
  ``TPU_CHIPS_PER_PROCESS_BOUNDS=2,2,1`` and ``TPU_PROCESS_BOUNDS=1,2,1``.
  """
  if num_chips < 1 or worker_index < 0 or workers_per_host < 1:
    raise ValueError("invalid chip allocation request: num_chips={} "
                     "worker_index={} workers_per_host={}".format(
                         num_chips, worker_index, workers_per_host))
  lo = (worker_index % workers_per_host) * num_chips
  chips = list(range(lo, lo + num_chips))
  if chips[-1] >= MAX_CHIPS_PER_HOST:
    raise ValueError(
        "worker {} requests chips {} but hosts have at most {} chips".format(
            worker_index, chips, MAX_CHIPS_PER_HOST))
  host_grid = _HOST_CHIP_GRID.get((generation or "").lower(), (2, 4))
  total_grid = _fit_grid(num_chips * workers_per_host, host_grid)
  chip_grid = _fit_grid(num_chips, total_grid) if total_grid else None
  if chip_grid is None:
    raise ValueError(
        "cannot tile {} chips x {} workers onto the {} host chip grid "
        "{}x{}".format(num_chips, workers_per_host, generation or "default",
                       host_grid[0], host_grid[1]))
  proc_grid = (total_grid[0] // chip_grid[0], total_grid[1] // chip_grid[1])
  addresses = ",".join(
      "{}:{}".format(host, base_port + i) for i in range(workers_per_host))
  local = worker_index % workers_per_host
  return {
      "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chips),
      "TPU_CHIPS_PER_PROCESS_BOUNDS": "{},{},1".format(*chip_grid),
      "TPU_PROCESS_BOUNDS": "{},{},1".format(*proc_grid),
      "TPU_PROCESS_ADDRESSES": addresses,
      "TPU_PROCESS_PORT": str(base_port + local),
      "CLOUD_TPU_TASK_ID": str(local),
  }


def apply_chip_env(env_updates: Dict[str, str]) -> None:
  """Apply allocation env (must run before JAX/libtpu initialization)."""
  os.environ.update(env_updates)
  logger.info("TPU chip allocation: %s", env_updates)
