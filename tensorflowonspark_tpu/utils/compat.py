"""Version/role compatibility shims for user main functions.

Parity with the reference's ``compat.py``
(/root/reference/tensorflowonspark/compat.py:10-31): the load-bearing behavior
is *chief-only export* — every worker calls ``export_saved_model`` but only the
chief writes to the real destination; non-chiefs write to a throwaway local dir
so collective-dependent export code still runs on all nodes. Here the exported
artifact is an Orbax checkpoint / flax state rather than a TF SavedModel.
"""

import logging
import tempfile

logger = logging.getLogger(__name__)


def jax_shard_map(*args, **kwargs):
  """``shard_map`` across jax versions.

  Newer jax exports it at top level (``from jax import shard_map``) and
  renamed ``check_rep`` to ``check_vma``; the version in this image still
  has the pre-promotion ``jax.experimental.shard_map`` with ``check_rep``.
  Every in-repo call site imports this shim (lazily, inside the function
  using it — jax must not be imported at orchestration-layer import time)
  and may pass either kwarg spelling.
  """
  try:
    from jax import shard_map
    legacy = False
  except ImportError:
    from jax.experimental.shard_map import shard_map
    legacy = True
  if legacy and "check_vma" in kwargs:
    kwargs["check_rep"] = kwargs.pop("check_vma")
  elif not legacy and "check_rep" in kwargs:
    kwargs["check_vma"] = kwargs.pop("check_rep")
  return shard_map(*args, **kwargs)


def jax_axis_size(axis_name):
  """``lax.axis_size`` across jax versions (use inside shard_map bodies).

  Newer jax has ``lax.axis_size(name)``; on the version in this image the
  classic ``psum(1, name)`` idiom serves — it constant-folds to a static
  python int under shard_map, so it remains usable as a loop bound.
  """
  from jax import lax
  if hasattr(lax, "axis_size"):
    return lax.axis_size(axis_name)
  return lax.psum(1, axis_name)


def export_model(state, export_dir: str, is_chief: bool) -> str:
  """Export model state; chief writes to ``export_dir``, others to a tmp dir.

  Args:
    state: a pytree of arrays (e.g. flax TrainState params) to save.
    export_dir: destination directory for the chief's export.
    is_chief: whether this process is chief/worker:0.

  Returns the directory actually written to.
  """
  import jax
  import numpy as np
  import orbax.checkpoint as ocp
  from tensorflowonspark_tpu.utils import paths

  # numpy SCALAR leaves (np.float32(3.0) — e.g. optimizer counts) are
  # rejected by current orbax; 0-d ndarrays round-trip identically
  state = jax.tree_util.tree_map(
      lambda x: np.asarray(x) if isinstance(x, np.generic) else x, state)
  target = export_dir if is_chief else tempfile.mkdtemp(prefix="nonchief_export_")
  ckptr = ocp.StandardCheckpointer()
  ckptr.save(paths.for_io(paths.join(target, "model")), state, force=True)
  ckptr.wait_until_finished()
  logger.info("exported model to %s (chief=%s)", target, is_chief)
  return target


def import_model(export_dir: str, template=None):
  """Load a model state previously written by :func:`export_model`."""
  import orbax.checkpoint as ocp
  from tensorflowonspark_tpu.utils import paths

  ckptr = ocp.StandardCheckpointer()
  path = paths.for_io(paths.join(export_dir, "model"))
  if template is not None:
    return ckptr.restore(path, template)
  return ckptr.restore(path)


def is_tpu_available() -> bool:
  """Accelerator-availability shim for user code (parity:
  reference compat.is_gpu_available, compat.py:27-31)."""
  from tensorflowonspark_tpu.utils import tpu_info
  return tpu_info.is_tpu_available()


def disable_auto_shard(options) -> None:
  """No-op on the JAX path (parity stub: reference compat.py:20-24).

  The reference disabled tf.data auto-sharding when feeding from Spark; the
  JAX feed plane shards explicitly by executor, so there is nothing to disable.
  """
  logger.debug("disable_auto_shard: no-op on the TPU/JAX path")
