"""Host-level utilities: IP discovery, port selection, executor-id persistence.

Capability parity with the reference's ``util.py``
(/root/reference/tensorflowonspark/util.py:52-94): ``get_ip_address`` (UDP-connect
trick), ``find_in_path``, and the executor-id file protocol that lets transient
data-feeding tasks locate the persistent per-host feed hub started by an earlier
task in the same working directory.
"""

import errno
import os
import socket
import logging

logger = logging.getLogger(__name__)

EXECUTOR_ID_FILE = "executor_id"


def get_ip_address() -> str:
  """Best-effort externally-routable IP of the current host.

  Uses the UDP-connect trick (no packets are actually sent); falls back to
  hostname resolution and finally loopback so single-host/dev environments
  (no network egress) still work.
  """
  try:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
      s.connect(("8.8.8.8", 80))
      return s.getsockname()[0]
    finally:
      s.close()
  except OSError:
    try:
      return socket.gethostbyname(socket.getfqdn())
    except OSError:
      return "127.0.0.1"


def get_free_port(host: str = "") -> int:
  """Bind an ephemeral TCP port, release it, and return its number."""
  s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
  try:
    s.bind((host, 0))
    return s.getsockname()[1]
  finally:
    s.close()


def find_in_path(path: str, file_name: str):
  """Find a file in a ':'-separated path string; return full path or False."""
  for p in path.split(os.pathsep):
    candidate = os.path.join(p, file_name)
    if os.path.exists(candidate) and os.path.isfile(candidate):
      return candidate
  return False


def single_node_env(num_chips: int = 0, worker_index: int = 0,
                    workers_per_host: int = 1) -> None:
  """Prepare this process's env for standalone single-node execution.

  Parity with the reference's ``util.single_node_env`` (util.py:21-49,
  which expanded the Hadoop classpath and set GPU visibility for one-off
  tasks): on TPU the equivalent is claiming a chip share for this process
  before any JAX/libtpu initialization.
  """
  from tensorflowonspark_tpu.utils import tpu_info
  if num_chips and not os.environ.get("TOS_TPU_TEST_MODE"):
    topo = tpu_info.get_topology()
    if topo is not None:
      tpu_info.apply_chip_env(tpu_info.chip_env_for_worker(
          num_chips, worker_index, workers_per_host,
          generation=topo.generation))


def write_executor_id(num: int, working_dir: str = ".") -> None:
  """Persist this executor's id to a file in the executor working dir.

  Later tasks scheduled onto the same executor (e.g. data-feeding tasks) read
  this file to find the feed hub owned by this executor (reference:
  util.py:77-94, consumed at TFSparkNode.py:482,614).
  """
  with open(os.path.join(working_dir, EXECUTOR_ID_FILE), "w") as f:
    f.write(str(num))


def read_executor_id(working_dir: str = ".") -> int:
  """Read the executor id written by :func:`write_executor_id`."""
  path = os.path.join(working_dir, EXECUTOR_ID_FILE)
  try:
    with open(path, "r") as f:
      return int(f.read())
  except OSError as e:
    if e.errno == errno.ENOENT:
      raise RuntimeError(
          "No executor_id file found in {}; the node runtime has not started "
          "on this executor".format(os.path.abspath(working_dir)))
    raise
