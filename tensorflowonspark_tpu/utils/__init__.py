"""L0' host/platform utilities.

Replaces the reference's ``util.py`` (/root/reference/tensorflowonspark/util.py),
``gpu_info.py`` (GPU discovery via nvidia-smi → here TPU chip/host discovery via
JAX/libtpu env) and ``compat.py``.
"""

from tensorflowonspark_tpu.utils.hostinfo import (  # noqa: F401
    get_ip_address,
    get_free_port,
    find_in_path,
    read_executor_id,
    write_executor_id,
)
from tensorflowonspark_tpu.utils import tpu_info  # noqa: F401
from tensorflowonspark_tpu.utils.paths import absolute_path  # noqa: F401
