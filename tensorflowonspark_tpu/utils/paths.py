"""Filesystem path normalization for heterogeneous storage schemes.

Capability parity with the reference's ``TFNode.hdfs_path``
(/root/reference/tensorflowonspark/TFNode.py:32-67), which normalized user
paths against the cluster default filesystem across 10 Hadoop schemes. The TPU
build targets GCS-first storage but keeps the same semantics: absolute scheme
URIs pass through, relative paths are anchored at the default FS + working dir.
"""

import logging

logger = logging.getLogger(__name__)

# schemes that pass through untouched
_PASSTHROUGH = ("gs://", "hdfs://", "viewfs://", "file://", "s3://", "s3a://",
                "s3n://", "maprfs://", "swift://", "wasb://", "abfs://")


def absolute_path(path: str, default_fs: str = "file://",
                  working_dir: str = ".") -> str:
  """Convert a possibly-relative ``path`` to an absolute URI.

  Args:
    path: user path; may carry an explicit scheme, be absolute, or relative.
    default_fs: cluster default filesystem URI (e.g. ``gs://bucket`` or
      ``file://``).
    working_dir: current working directory used to anchor relative local paths.
  """
  if any(path.startswith(s) for s in _PASSTHROUGH):
    return path
  if path.startswith("/"):
    # absolute path on the default FS
    if default_fs.startswith("file://"):
      return "file://" + path
    return default_fs.rstrip("/") + path
  # relative path
  if default_fs.startswith("file://"):
    import os
    return "file://" + os.path.join(os.path.abspath(working_dir), path)
  return default_fs.rstrip("/") + "/" + path


def strip_scheme(path: str) -> str:
  """Drop a ``file://`` scheme so the path can be used with local IO."""
  if path.startswith("file://"):
    return path[len("file://"):]
  return path


def is_remote_uri(path: str) -> bool:
  """True for non-local scheme URIs (gs://, hdfs://, s3://, ...)."""
  return any(path.startswith(s) for s in _PASSTHROUGH if s != "file://")


def for_io(path: str) -> str:
  """Normalize a storage target for IO libraries (orbax/tensorstore).

  Remote scheme URIs pass through untouched — orbax handles ``gs://`` etc.
  natively, and ``os.path.abspath`` would mangle them into bogus local
  paths. Local paths (with or without ``file://``) become absolute.
  """
  if is_remote_uri(path):
    return path
  import os
  return os.path.abspath(strip_scheme(path))


def join(path: str, *parts: str) -> str:
  """Scheme-aware join: ``/`` for remote URIs, ``os.path.join`` locally."""
  if is_remote_uri(path):
    return "/".join([path.rstrip("/")] + list(parts))
  import os
  return os.path.join(path, *parts)
