"""Process-level JAX platform sanitization for CPU-only multi-device work.

Some sandboxes install a sitecustomize hook that registers a remote-TPU PJRT
plugin at interpreter start whenever ``PALLAS_AXON_POOL_IPS`` is set; the
plugin's ``register()`` force-sets ``jax_platforms="axon,cpu"`` (overriding the
``JAX_PLATFORMS`` env var), so any backend initialization dials a remote chip
for a claim. Multi-process CPU drives must defeat that, or N concurrent
processes all claim the single remote chip and wedge it.

One shared implementation for tests (``tests/conftest.py``), the driver entry
(``__graft_entry__.py``), the ``Makefile`` dryrun, and the example scripts —
keeps them from drifting. Must be called before jax's backend initializes;
raises if that already happened with the wrong platform, because silently
proceeding would dial the remote TPU.
"""

import os
import re


def _backend_initialized() -> bool:
  """True when jax's backend is already up (best effort — private API)."""
  try:
    from jax._src import xla_bridge
    return bool(xla_bridge.backends_are_initialized())
  except Exception:  # noqa: BLE001 - private API; degrade to "unknown"
    return False


def _strip_axon(platforms: str) -> str:
  return ",".join(p for p in platforms.split(",") if p and p != "axon")


def drop_remote_plugin() -> None:
  """Strip the sandbox's remote-TPU plugin without otherwise forcing a
  platform.

  For example/driver scripts that should run on whatever real hardware the
  host has (CPU locally, real TPUs on a pod) but must never dial the
  sandbox's single remote chip. No-op outside the sandbox. Children inherit
  the cleaned environment, so spawned executors are safe too.

  Raises:
    RuntimeError: if jax already initialized its backend on the remote
      plugin — too late to redirect; sanitize earlier.
  """
  os.environ.pop("PALLAS_AXON_POOL_IPS", None)
  env_platforms = os.environ.get("JAX_PLATFORMS")
  if env_platforms is not None and "axon" in env_platforms.split(","):
    stripped = _strip_axon(env_platforms)
    if stripped:
      os.environ["JAX_PLATFORMS"] = stripped
    else:
      del os.environ["JAX_PLATFORMS"]
  try:
    import jax
  except ImportError:
    return  # nothing registered yet; the env cleanup above is sufficient
  configured = jax.config.jax_platforms or ""
  if "axon" in configured.split(","):
    if _backend_initialized():
      raise RuntimeError(
          "drop_remote_plugin called after jax initialized the %r backend — "
          "sanitize before any jax computation" % configured)
    jax.config.update("jax_platforms", _strip_axon(configured) or None)


def force_cpu_platform(n_devices: int = 8) -> None:
  """Force this process onto a virtual CPU platform of >= ``n_devices``.

  A caller-supplied ``--xla_force_host_platform_device_count`` larger than
  ``n_devices`` is preserved (so e.g. ``XLA_FLAGS=...=16 pytest`` still sees
  16 devices); a smaller one is grown to ``n_devices``. Safe to call multiple
  times. Child processes inherit the environment, so calling this before
  spawning executors keeps the whole tree CPU-only.

  Raises:
    RuntimeError: if jax's backend was already initialized on a non-CPU
      platform or with too few devices (too late to redirect — the caller
      must sanitize earlier).
  """
  os.environ.pop("PALLAS_AXON_POOL_IPS", None)
  os.environ["JAX_PLATFORMS"] = "cpu"
  flags = os.environ.get("XLA_FLAGS", "")
  existing = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
  count = max(n_devices, int(existing.group(1)) if existing else 0)
  opt = "--xla_force_host_platform_device_count=%d" % count
  if existing:
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
  else:
    flags = (flags + " " + opt).strip()
  os.environ["XLA_FLAGS"] = flags

  try:
    import jax
  except ImportError:
    return  # nothing registered yet; the env vars above are sufficient
  # Undo the plugin's force-set config (sitecustomize already ran register()
  # in this process; the env var alone no longer wins).
  jax.config.update("jax_platforms", "cpu")
  if _backend_initialized():
    if jax.default_backend() != "cpu":
      raise RuntimeError(
          "force_cpu_platform called after jax initialized backend %r — "
          "sanitize before any jax computation" % jax.default_backend())
    if jax.device_count() < n_devices:
      raise RuntimeError(
          "force_cpu_platform: jax already initialized with %d CPU devices, "
          "cannot grow to %d — sanitize before any jax computation"
          % (jax.device_count(), n_devices))
