"""Checkpoint/resume: a thin manager over orbax with step tracking.

The reference delegated checkpointing to user TF callbacks and only
provided path plumbing + an export grace window (SURVEY.md §5
checkpoint/resume). This module keeps that division of labor but gives the
JAX path a ready-made manager: periodic saves keyed by step, latest-step
restore for resume-after-preemption, retention, and chief-only writes.
"""

import json
import logging
import os
from typing import Any, Optional

logger = logging.getLogger(__name__)

#: commit-marker file written next to each step after its save is durable.
#: Its presence IS the commit record: ``restore_or`` rejects a step with no
#: marker deterministically (torn save) instead of discovering the tear via
#: a deserialize failure, and its JSON body carries the save's manifest
#: (e.g. the elastic-training group topology — ``parallel.groups``).
_MARKER_FMT = ".commit-%d.json"
_MARKER_PREFIX = ".commit-"
_MARKER_SUFFIX = ".json"


def atomic_write_json(path: str, payload: dict) -> None:
  """Commit ``payload`` to ``path`` via write-to-temp + fsync + atomic
  rename — THE torn-write-proof marker protocol. A kill at any point
  leaves either no file or a complete one, never a half-written record.

  This is the single implementation behind the checkpoint commit markers
  and the model-registry publish markers (``serving.registry``): two
  independent torn-write protocols must not drift, so both call here.
  Raises ``OSError`` on failure — callers decide whether a marker-write
  failure fails the operation.
  """
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    json.dump(payload, f)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)


def params_fingerprint(tree: Any) -> str:
  """Cheap content fingerprint of a params pytree: crc32 over every
  leaf's bytes folded with its flattened path, shape, and dtype.

  Shared by the model registry (publish manifest / poisoned-candidate
  detection) and ``make_serving_predict_fn``'s engine-cache key, so "same
  weights" means the same thing on both sides of the train→serve loop.
  Not cryptographic — this guards against torn publishes and stale cache
  hits, not adversaries.
  """
  import zlib
  import jax
  import numpy as np
  acc = 0
  leaves, treedef = jax.tree_util.tree_flatten(tree)
  acc = zlib.crc32(repr(treedef).encode(), acc)
  for leaf in leaves:
    arr = np.asarray(leaf)
    acc = zlib.crc32(str((arr.shape, str(arr.dtype))).encode(), acc)
    acc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), acc)
  return "%08x" % (acc & 0xFFFFFFFF)


class CheckpointManager(object):
  """Periodic save / latest restore of a train-state pytree.

  Usage::

      mgr = CheckpointManager(args.model_dir, save_interval_steps=100)
      state, start_step = mgr.restore_or(state)     # resume if possible
      for step in range(start_step, num_steps):
          state, loss = train_step(state, batch)
          mgr.save(step, state, is_chief=ctx.is_chief)
      mgr.wait()

  With a checkpointable input pipeline (exact mid-epoch resume)::

      it = data.checkpointable_input(pattern, batch_size, seed=0)
      state, start_step = mgr.restore_or(state, data_iterator=it)
      for step, batch in enumerate(it, start=start_step):
          state, loss = train_step(state, batch)
          mgr.save(step, state, data_state=it.get_state())
  """

  def __init__(self, directory: str, save_interval_steps: int = 100,
               max_to_keep: int = 3, publish_hook: Optional[Any] = None):
    import orbax.checkpoint as ocp
    from tensorflowonspark_tpu.utils import paths

    self.directory = paths.for_io(directory)
    # commit markers are plain files: local directories only (remote URIs
    # keep the legacy deserialize-failure fallback in restore_or)
    self._local = not paths.is_remote_uri(self.directory)
    if self._local:
      os.makedirs(self.directory, exist_ok=True)
    self.save_interval_steps = save_interval_steps
    #: ``publish_hook(step, state, manifest)`` fires after a save COMMITS
    #: (marker durable) — the train→serve seam. A registry attaches one
    #: via ``serving.registry.ModelRegistry.publish_on_checkpoint`` so
    #: every committed checkpoint becomes a candidate serving version on
    #: the existing cadence. Best-effort: a publish failure is logged,
    #: never fails the save (the checkpoint itself is already durable).
    self.publish_hook = publish_hook
    self._mgr = ocp.CheckpointManager(
        self.directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps))

  def save(self, step: int, state: Any, is_chief: bool = True,
           force: bool = False, data_state: Optional[dict] = None,
           manifest: Optional[dict] = None) -> bool:
    """Save if the step hits the interval.

    ``data_state`` (a small JSON-safe dict, e.g.
    ``data.indexed.CheckpointableInput.get_state()``) rides in the same
    checkpoint as a named item, so model and input-pipeline state stay
    atomically consistent — resume continues mid-epoch with exactly the
    batches the uninterrupted run would have seen (the reference got this
    from tf.train.Checkpoint over tf.data iterators; the feed mode had no
    equivalent).

    Role handling depends on the process topology: in a jax.distributed
    process group, orbax's save is a COLLECTIVE — every process must call
    it (orbax writes from the primary host only), so ``is_chief`` is
    ignored there. For independent single-process nodes (no process
    group), only the chief writes (parity with chief-only export,
    reference compat.py:10-17).

    The save decision is interval-CROSSING, not modulo: a fused train
    loop calls this once per slab with ``step`` jumping ``unroll`` at a
    time, and orbax's own ``step % interval == 0`` rule would silently
    stretch the cadence to the steps' common multiples (``unroll=8``
    with ``save_interval_steps=5`` would save every 40 steps — or
    never, for coprime pairs past max step). Here the save fires at the
    FIRST call whose step reached/passed an interval boundary since the
    last saved step — step-accurate at slab boundaries, and identical
    to the old behavior for dense per-step calls.
    """
    import jax
    if not is_chief and jax.process_count() <= 1:
      return False
    if not force and not self._due(step):
      return False
    import orbax.checkpoint as ocp
    items = {"state": ocp.args.StandardSave(state)}
    if data_state is not None:
      items["data"] = ocp.args.JsonSave(data_state)
    try:
      # force=True: the interval decision was made above (orbax's modulo
      # rule would re-filter boundary-crossing slab steps right back out)
      saved = self._mgr.save(step, args=ocp.args.Composite(**items),
                             force=True)
    except ValueError:
      # a directory written by the pre-composite manager pins orbax to
      # the single-unnamed-item layout; keep appending in that layout
      if data_state is not None:
        logger.warning("legacy checkpoint layout in %s cannot carry "
                       "data_state; saving model state only",
                       self.directory)
      saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                             force=True)
    if saved:
      self._write_marker(step, manifest)
      logger.info("checkpoint saved at step %d", step)
      if self.publish_hook is not None:
        try:
          self.publish_hook(step, state, manifest)
        except Exception as e:  # noqa: BLE001 # tosa: ignore[TOS004] - best-effort
          # publish is best-effort: the checkpoint committed; a
          # registry outage must not fail it (serving has watch/resume)
          logger.warning("publish hook at step %d failed: %s: %s",
                         step, type(e).__name__, e)
    return saved

  # -- commit markers (deterministic torn-save detection) ---------------------

  def _marker_path(self, step: int) -> str:
    return os.path.join(self.directory, _MARKER_FMT % step)

  def _write_marker(self, step: int, manifest: Optional[dict]) -> None:
    """Commit the save: wait for the (possibly async) write to be durable,
    then publish the marker via write-to-temp + atomic rename. A kill at
    any point leaves either no marker (torn save, rejected at restore) or
    a complete one — never a half-written marker next to half-written
    data. ``manifest`` (small, JSON-safe — e.g. the group topology from
    ``parallel.groups.GroupSet.save``) rides in the marker body."""
    if not self._local:
      return
    self._mgr.wait_until_finished()
    path = self._marker_path(step)
    try:
      atomic_write_json(path, {"step": int(step), "manifest": manifest or {}})
    except OSError as e:
      # the data is durable; a marker-write failure must not fail the save
      # (the step merely restores via nothing — same as a torn save)
      logger.warning("commit marker for step %d failed: %s", step, e)
      return
    # retention pruning: drop markers whose step orbax already deleted
    live = set(self._mgr.all_steps())
    try:
      names = os.listdir(self.directory)
    except OSError:
      return
    for name in names:
      if not (name.startswith(_MARKER_PREFIX)
              and name.endswith(_MARKER_SUFFIX)):
        continue
      try:
        s = int(name[len(_MARKER_PREFIX):-len(_MARKER_SUFFIX)])
      except ValueError:
        continue
      if s not in live:
        try:
          os.remove(os.path.join(self.directory, name))
        except OSError:  # tosa: ignore[TOS004] - retention pruning is
          pass           # best-effort; a leftover marker is harmless

  def _has_markers(self) -> bool:
    """True when this directory uses commit markers at all (any step has
    one). Marker-free directories predate the marker scheme and keep the
    legacy deserialize-failure fallback."""
    if not self._local:
      return False
    try:
      return any(n.startswith(_MARKER_PREFIX) and n.endswith(_MARKER_SUFFIX)
                 for n in os.listdir(self.directory))
    except OSError:
      return False

  def _read_marker(self, step: int) -> Optional[dict]:
    """The step's commit record, or None (missing or unparseable — both
    mean the save never committed)."""
    if not self._local:
      return None
    try:
      with open(self._marker_path(step)) as f:
        return json.load(f)
    except (OSError, ValueError):
      return None

  def manifest(self, step: Optional[int] = None) -> Optional[dict]:
    """The manifest committed with ``step`` (default: latest), or None."""
    step = step if step is not None else self._mgr.latest_step()
    if step is None:
      return None
    rec = self._read_marker(step)
    return rec.get("manifest") if rec else None

  def _due(self, step: int) -> bool:
    """True when ``step`` reached/crossed an interval boundary since the
    last saved step (always for the first save; never for non-advancing
    steps). A signalled preemption is always due — taking the interval
    decision out of orbax's hands must not lose its save-on-preemption
    behavior for mid-interval steps."""
    last = self._mgr.latest_step()
    if last is not None and step <= last:
      return False
    # the same call orbax's own should_save made on this path before the
    # crossing rule replaced it (getattr: older orbax lacks the method)
    reached = getattr(self._mgr, "reached_preemption", None)
    if reached is not None and reached(step):
      return True
    if last is None:
      return True
    interval = max(1, int(self.save_interval_steps))
    return (step // interval) > (last // interval)

  def latest_step(self, refresh: bool = False) -> Optional[int]:
    """Newest checkpointed step, or None.

    orbax caches the directory's step listing at construction and after
    its own saves — a manager that only READS (the evaluator-sidecar
    pattern: another process writes the checkpoints) must pass
    ``refresh=True`` to rescan, or it will report the world as of its
    own birth forever.
    """
    if refresh:
      try:
        self._mgr.reload()
      except AttributeError:   # older orbax: no reload(); best effort
        pass
    return self._mgr.latest_step()

  def restore(self, state_template: Any, step: Optional[int] = None,
              with_data: bool = False) -> Any:
    """Restore the given (or latest) step into the template's structure.

    ``with_data=True`` returns ``(state, data_state_or_None)`` — None when
    the checkpoint carries no input-pipeline item (legacy layout, or saved
    without ``data_state``).
    """
    import orbax.checkpoint as ocp
    step = step if step is not None else self._mgr.latest_step()
    if step is None:
      raise FileNotFoundError("no checkpoints in %s" % self.directory)
    try:
      out = self._mgr.restore(step, args=ocp.args.Composite(
          state=ocp.args.StandardRestore(state_template)))
      state = out["state"]
    except ValueError:
      # pre-composite layout: the whole checkpoint IS the model state
      state = self._mgr.restore(
          step, args=ocp.args.StandardRestore(state_template))
      return (state, None) if with_data else state
    if not with_data:
      return state
    try:
      data = self._mgr.restore(
          step, args=ocp.args.Composite(data=ocp.args.JsonRestore()))["data"]
    except KeyError:
      data = None
    return state, data

  def restore_or(self, state: Any, data_iterator: Any = None,
                 with_manifest: bool = False):
    """(state, next_step): restored latest if present, else the input.

    With ``data_iterator`` (anything exposing ``set_state``, e.g.
    ``CheckpointableInput``), a checkpointed input-pipeline state is
    pushed into it so the stream resumes mid-epoch. With
    ``with_manifest=True`` the return is ``(state, next_step, manifest)``
    — the commit marker's manifest dict (None when absent or fresh).

    Preemption-safe: this is the resume entry point for a node relaunched
    after a SIGKILL/preemption (the supervisor hands the restart count to
    the user fn via ``ctx.restart_count``). In a directory that carries
    commit markers, a step with NO marker never committed — it is
    rejected deterministically, without a restore attempt whose failure
    mode depends on how the storage layer surfaces the tear. Marker-free
    (legacy) directories keep the old behavior: a checkpoint left
    unreadable by a kill mid-save is skipped with a warning after its
    deserialize fails, falling back to the newest step that restores
    cleanly rather than wedging the relaunched node forever.
    """
    step = self._mgr.latest_step()
    last_error = None
    markers = self._has_markers()
    while step is not None:
      if markers and self._read_marker(step) is None:
        logger.warning("checkpoint step %d has no commit marker (torn "
                       "save); rejecting it without a restore attempt", step)
        last_error = RuntimeError(
            "checkpoint step %d in %s has no commit marker"
            % (step, self.directory))
        older = [s for s in self._mgr.all_steps() if s < step]
        step = max(older) if older else None
        continue
      logger.info("resuming from checkpoint step %d", step)
      try:
        if data_iterator is None:
          restored = self.restore(state, step=step)
        else:
          restored, data = self.restore(state, step=step, with_data=True)
          if data is not None:
            data_iterator.set_state(data)
          else:
            logger.warning("checkpoint step %d has no input-pipeline state; "
                           "the data iterator starts from its current "
                           "position", step)
        if with_manifest:
          return restored, step + 1, self.manifest(step)
        return restored, step + 1
      except Exception as e:  # noqa: BLE001 - torn/corrupt checkpoint
        logger.warning("checkpoint step %d unreadable (%s: %s); trying the "
                       "previous step", step, type(e).__name__, e)
        last_error = e
        older = [s for s in self._mgr.all_steps() if s < step]
        step = max(older) if older else None
    if last_error is not None:
      # EVERY step failed to restore: that is a systemic problem (template
      # mismatch, storage outage, bad credentials), not a torn checkpoint
      # — silently retraining from step 0 would discard real progress
      raise last_error
    return (state, 0, None) if with_manifest else (state, 0)

  def all_steps(self):
    """Every step with a checkpoint in this directory (ascending)."""
    return sorted(self._mgr.all_steps())

  def wait(self) -> None:
    """Block until async saves land (call before process exit)."""
    self._mgr.wait_until_finished()

  def close(self) -> None:
    self._mgr.close()
