"""Checkpoint/resume: a thin manager over orbax with step tracking.

The reference delegated checkpointing to user TF callbacks and only
provided path plumbing + an export grace window (SURVEY.md §5
checkpoint/resume). This module keeps that division of labor but gives the
JAX path a ready-made manager: periodic saves keyed by step, latest-step
restore for resume-after-preemption, retention, and chief-only writes.
"""

import logging
import os
from typing import Any, Optional

logger = logging.getLogger(__name__)


class CheckpointManager(object):
  """Periodic save / latest restore of a train-state pytree.

  Usage::

      mgr = CheckpointManager(args.model_dir, save_interval_steps=100)
      state, start_step = mgr.restore_or(state)     # resume if possible
      for step in range(start_step, num_steps):
          state, loss = train_step(state, batch)
          mgr.save(step, state, is_chief=ctx.is_chief)
      mgr.wait()
  """

  def __init__(self, directory: str, save_interval_steps: int = 100,
               max_to_keep: int = 3):
    import orbax.checkpoint as ocp
    from tensorflowonspark_tpu.utils import paths

    self.directory = paths.for_io(directory)
    if not paths.is_remote_uri(self.directory):
      os.makedirs(self.directory, exist_ok=True)
    self.save_interval_steps = save_interval_steps
    self._mgr = ocp.CheckpointManager(
        self.directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps))

  def save(self, step: int, state: Any, is_chief: bool = True,
           force: bool = False) -> bool:
    """Save if the step hits the interval.

    Role handling depends on the process topology: in a jax.distributed
    process group, orbax's save is a COLLECTIVE — every process must call
    it (orbax writes from the primary host only), so ``is_chief`` is
    ignored there. For independent single-process nodes (no process
    group), only the chief writes (parity with chief-only export,
    reference compat.py:10-17).
    """
    import jax
    if not is_chief and jax.process_count() <= 1:
      return False
    import orbax.checkpoint as ocp
    saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                           force=force)
    if saved:
      logger.info("checkpoint saved at step %d", step)
    return saved

  def latest_step(self, refresh: bool = False) -> Optional[int]:
    """Newest checkpointed step, or None.

    orbax caches the directory's step listing at construction and after
    its own saves — a manager that only READS (the evaluator-sidecar
    pattern: another process writes the checkpoints) must pass
    ``refresh=True`` to rescan, or it will report the world as of its
    own birth forever.
    """
    if refresh:
      try:
        self._mgr.reload()
      except AttributeError:   # older orbax: no reload(); best effort
        pass
    return self._mgr.latest_step()

  def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
    """Restore the given (or latest) step into the template's structure."""
    import orbax.checkpoint as ocp
    step = step if step is not None else self._mgr.latest_step()
    if step is None:
      raise FileNotFoundError("no checkpoints in %s" % self.directory)
    return self._mgr.restore(step,
                             args=ocp.args.StandardRestore(state_template))

  def restore_or(self, state: Any):
    """(state, next_step): restored latest if present, else the input."""
    step = self._mgr.latest_step()
    if step is None:
      return state, 0
    logger.info("resuming from checkpoint step %d", step)
    return self.restore(state), step + 1

  def wait(self) -> None:
    """Block until async saves land (call before process exit)."""
    self._mgr.wait_until_finished()

  def close(self) -> None:
    self._mgr.close()
