"""Deterministic fault injection for exercising the fault-tolerance runtime.

On TPU pods, preemption and single-host failure are the common case, not the
exception — so every recovery path (missed-beat detection, supervised node
relaunch, checkpoint resume, feed requeue) must be exercisable by ordinary
CPU tests. This module provides the injection points; the recovery machinery
lives in ``control.rendezvous`` (liveness), ``cluster.ClusterSupervisor``
(relaunch) and ``engine.local`` (executor respawn).

Faults are armed via environment variables, so they flow naturally into
engine executor processes (``LocalEngine(env=...)``, Spark executor env)
and every child they spawn. All triggers are DETERMINISTIC: named injection
points fire on exact invocation counts, never at random.

Env vars (all optional; absent ⇒ every hook is a no-op):

``TOS_CHAOS_KILL`` = ``"point[@index][#nth]"`` (comma-separated specs)
    SIGKILL the calling process the nth time (default: 1st) the named
    :func:`kill_point` fires with a matching index. Example:
    ``"train-step@0#3"`` kills executor 0 the 3rd time it reaches the
    ``train-step`` point — i.e. *kill node N at step S*. Exactly-once
    across process restarts: a sentinel file in the working directory
    records the fire, so a relaunched node sails past the same point.

``TOS_CHAOS_STALL`` = ``"point[@index]:seconds"`` (comma-separated)
    Sleep at the named :func:`stall_point` (first matching call per
    process) — e.g. ``"feeder@1:3"`` stalls executor 1's feed task.

``TOS_CHAOS_RV_DROP`` = ``"VERB:count"`` (comma-separated)
    Client-side rendezvous fault: silently drop the first ``count``
    messages of the given verb before they hit the wire — e.g.
    ``"BEAT:3"`` makes the server miss three heartbeats.

``TOS_CHAOS_RV_DELAY`` = ``"VERB:seconds[:count]"`` (comma-separated)
    Client-side rendezvous fault: delay messages of the given verb by
    ``seconds`` before sending (first ``count`` messages; default: all).

``TOS_CHAOS_SERVE`` = ``"point[@index][#nth]:raise"`` or
    ``"point[@index][#nth]:stall:seconds"`` (comma-separated)
    Serving-plane fault at a named :func:`serve_fault` point
    (``serving.slots`` arms ``prefill`` and ``decode``): ``raise``
    throws :class:`InjectedFault` into the engine loop the nth time the
    point fires (exercising crash-replay recovery), ``stall`` sleeps
    there (a hung device call; exercising deadlines). Without
    ``@index`` the nth count is global across the point; with it, the
    count is per caller-supplied index — the ``prefill`` point passes
    the PROMPT LENGTH, the only stable pre-assignment identity a spec
    can name, so ``"prefill@13#1:raise,prefill@13#2:raise"`` makes every
    length-13 prompt a deterministic poison request while its neighbors
    sail through (docs/ROBUSTNESS.md).

``TOS_CHAOS_FLEET`` = ``"point[@replica][#nth]:kill"`` or
    ``"point[@replica][#nth]:stall:seconds"`` (comma-separated)
    Replica-granularity fault at a named :func:`fleet_fault` point
    (``serving.fleet`` arms ``dispatch`` with the replica id as index):
    ``kill`` tells the caller to terminally kill that REPLICA the nth
    time the point fires — e.g. ``"dispatch@1#3:kill"`` kills replica 1
    at its 3rd dispatch, with everything it already accepted mid-decode
    (exercising ejection + cross-replica failover replay); ``stall``
    sleeps at the dispatch (a slow router hop). Without ``@replica``
    the nth count is global across all dispatches.

``TOS_CHAOS_DEPLOY`` = ``"point[@index][#nth]:kill"``,
    ``"...:poison"`` or ``"...:stall:seconds"`` (comma-separated)
    Deployment-plane fault at a named :func:`deploy_fault` point
    (``serving.deploy`` arms ``canary``, ``verify``, ``promote`` and
    ``rollback`` — promote passes the replica id being swapped as index;
    the others pass the candidate version): ``kill`` tells the caller
    the driver-side controller dies AT that boundary (exercising
    recovery/resume convergence: zero shed, one consistent served
    version — e.g. ``"promote#1:kill"`` kills the controller mid-promote
    after the first remaining replica swaps); ``poison`` corrupts the
    CANDIDATE's params at the canary build (a bad publish VERIFY must
    catch: parity fails, the version is quarantined, never promoted);
    ``stall`` sleeps at the boundary (a slow controller hop).

``TOS_CHAOS_HOST`` = ``"point[@host][#nth]:kill"``,
    ``"...:stall:seconds"`` or ``"...:partition:seconds"`` (comma-sep)
    Host-granularity fault for the cross-host serving plane
    (``serving.host`` consults :func:`host_fault` at each ``sync``
    round with the host id as index — point ``sync`` ticks every
    round, point ``decode`` only on rounds with requests in flight,
    so a ``decode`` kill lands mid-decode by construction however
    long the engine build took): ``kill`` SIGKILLs the whole
    ServingHost EXECUTOR PROCESS at that boundary — engine, accepted
    requests, rendezvous client, everything, exactly like a preempted
    host (the driver-side fleet must eject its RemoteReplica and
    failover-replay bit-identically; docs/ROBUSTNESS.md §Cross-host
    serving); ``stall`` sleeps the host's sync loop inline (a slow
    host; the engine keeps decoding, the wire goes quiet briefly);
    ``partition`` makes the host skip ALL wire I/O for ``seconds``
    while the engine keeps decoding — a network partition, not a
    death: tokens buffer host-side and the driver sees silence, so
    past ``TOS_HOST_TIMEOUT`` the partition is indistinguishable from
    host death and MUST be handled identically (ejection + replay).
    E.g. ``"sync@1#30:kill"`` kills host 1 at its 30th sync round;
    ``"decode@1#3:kill"`` kills host 1 on its 3rd sync round with
    live requests — i.e. *kill host N mid-decode*.

``TOS_CHAOS_GROUP`` = ``"kill[@group][#nth]"`` or
    ``"stall[@group][#nth]:seconds"`` (comma-separated)
    Group-granularity fault for elastic multi-group training
    (``parallel.groups`` consults :func:`group_fault` at each sync-round
    boundary with the group id as index): ``kill`` stops that whole MESH
    GROUP mid-training — it never contributes to the round, so the
    surviving groups complete the sync with the denominator shrunk and
    the plane marks it lost (exercising graceful degradation + resize /
    re-admission); ``stall`` sleeps the group at the boundary (a slow or
    partitioned group; exercising the sync deadline). Without ``@group``
    the nth count is global across all boundary consults.
"""

import logging
import os
import re
import signal
import threading
import time
from typing import Optional, Tuple

logger = logging.getLogger(__name__)

ENV_KILL = "TOS_CHAOS_KILL"
ENV_STALL = "TOS_CHAOS_STALL"
ENV_RV_DROP = "TOS_CHAOS_RV_DROP"
ENV_RV_DELAY = "TOS_CHAOS_RV_DELAY"
ENV_SERVE = "TOS_CHAOS_SERVE"
ENV_FLEET = "TOS_CHAOS_FLEET"
ENV_GROUP = "TOS_CHAOS_GROUP"
ENV_DEPLOY = "TOS_CHAOS_DEPLOY"
ENV_HOST = "TOS_CHAOS_HOST"


class InjectedFault(RuntimeError):
  """The exception a ``raise``-action serving fault throws — a stand-in
  for any device/runtime error escaping the engine loop thread."""

# per-process invocation counters, keyed by (point, index)
_counts = {}
_stalled = set()
_rv_counts = {}
_lock = threading.Lock()

_KNOWN_ENV = (ENV_KILL, ENV_STALL, ENV_RV_DROP, ENV_RV_DELAY, ENV_SERVE,
              ENV_FLEET, ENV_GROUP, ENV_DEPLOY, ENV_HOST)
_ENV_PREFIX = "TOS_CHAOS_"
#: cache of the last validated env signature (validation is consulted from
#: hot paths like the rendezvous client's per-request chaos check)
_validated = None
#: first-consult guard: hooks fast-path on their OWN env var, so with only
#: a typo'd TOS_CHAOS_* name set every hook would return before reaching
#: check_config — scanned once per process (reset() re-arms)
_first_consult_done = False


def _first_consult():
  global _first_consult_done
  if _first_consult_done:
    return
  _first_consult_done = True
  if any(k.startswith(_ENV_PREFIX) for k in os.environ):
    check_config()


def check_config() -> None:
  """Validate every armed fault schedule; raise ValueError on bad config.

  A chaos run with a typo'd knob used to be a silent no-op twice over: an
  unknown ``TOS_CHAOS_*`` name was never read, and a malformed spec value
  was skipped by the parser (``"BEAT;3"`` simply never matched) — the test
  then 'passed' without injecting anything. Every hook entry point calls
  this, so fault schedules are asserted the first time chaos is consulted
  in a process (and again whenever the env signature changes).
  """
  global _validated
  sig = tuple(os.environ.get(k) for k in _KNOWN_ENV) + tuple(
      sorted(k for k in os.environ if k.startswith(_ENV_PREFIX)))
  if sig == _validated:
    return
  unknown = sorted(k for k in os.environ
                   if k.startswith(_ENV_PREFIX) and k not in _KNOWN_ENV)
  if unknown:
    raise ValueError(
        "unknown chaos env var(s) %s — known knobs: %s (a typo'd name "
        "would silently inject nothing)" % (unknown, list(_KNOWN_ENV)))
  for spec in _split_specs(os.environ.get(ENV_KILL)):
    try:
      _parse_point_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed kill spec %r (want "
                       "'point[@index][#nth]')" % (ENV_KILL, spec))
  for spec in _split_specs(os.environ.get(ENV_STALL)):
    try:
      _parse_stall_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed stall spec %r (want "
                       "'point[@index]:seconds')" % (ENV_STALL, spec))
  for spec in _split_specs(os.environ.get(ENV_RV_DROP)):
    try:
      _parse_drop_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed drop spec %r (want 'VERB:count')"
                       % (ENV_RV_DROP, spec))
  for spec in _split_specs(os.environ.get(ENV_RV_DELAY)):
    try:
      _parse_delay_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed delay spec %r (want "
                       "'VERB:seconds[:count]')" % (ENV_RV_DELAY, spec))
  for spec in _split_specs(os.environ.get(ENV_SERVE)):
    try:
      _parse_serve_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed serve spec %r (want "
                       "'point[@index][#nth]:raise' or "
                       "'point[@index][#nth]:stall:seconds')"
                       % (ENV_SERVE, spec))
  for spec in _split_specs(os.environ.get(ENV_FLEET)):
    try:
      _parse_fleet_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed fleet spec %r (want "
                       "'point[@replica][#nth]:kill' or "
                       "'point[@replica][#nth]:stall:seconds')"
                       % (ENV_FLEET, spec))
  for spec in _split_specs(os.environ.get(ENV_GROUP)):
    try:
      _parse_group_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed group spec %r (want "
                       "'kill[@group][#nth]' or "
                       "'stall[@group][#nth]:seconds')"
                       % (ENV_GROUP, spec))
  for spec in _split_specs(os.environ.get(ENV_DEPLOY)):
    try:
      _parse_deploy_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed deploy spec %r (want "
                       "'point[@index][#nth]:kill', '...:poison' or "
                       "'...:stall:seconds')" % (ENV_DEPLOY, spec))
  for spec in _split_specs(os.environ.get(ENV_HOST)):
    try:
      _parse_host_spec(spec)
    except ValueError:
      raise ValueError("%s: malformed host spec %r (want "
                       "'point[@host][#nth]:kill', '...:stall:seconds' or "
                       "'...:partition:seconds')" % (ENV_HOST, spec))
  _validated = sig


def _split_specs(env_value):
  if not env_value:
    return []
  return [s.strip() for s in env_value.split(",") if s.strip()]


def enabled() -> bool:
  """True when any chaos env var is armed (cheap fast-path guard)."""
  _first_consult()
  armed = any(os.environ.get(k) for k in _KNOWN_ENV)
  if armed:
    check_config()
  return armed


def reset() -> None:
  """Forget per-process counters (test isolation helper)."""
  global _validated, _first_consult_done
  with _lock:
    _counts.clear()
    _stalled.clear()
    _rv_counts.clear()
    _validated = None
    _first_consult_done = False


def _parse_point_spec(spec: str):
  """``"name[@index][#nth]"`` → (name, index_or_None, nth)."""
  nth = 1
  if "#" in spec:
    spec, n = spec.rsplit("#", 1)
    nth = int(n)
  index = None
  if "@" in spec:
    spec, i = spec.rsplit("@", 1)
    index = int(i)
  return spec, index, nth


# One parse function per knob grammar, shared by check_config AND the hooks
# — a validator that re-implemented the grammar could accept a spec the hook
# then silently never matched (the no-op class this module exists to kill).

def _parse_stall_spec(spec: str):
  """``"point[@index]:seconds"`` → ((name, index, nth), seconds)."""
  if ":" not in spec:
    raise ValueError(spec)
  target, secs = spec.rsplit(":", 1)
  return _parse_point_spec(target), float(secs)


def _parse_drop_spec(spec: str):
  """``"VERB:count"`` → (verb, count)."""
  parts = spec.split(":")
  if len(parts) != 2 or not parts[0]:
    raise ValueError(spec)
  return parts[0], int(parts[1])


def _parse_delay_spec(spec: str):
  """``"VERB:seconds[:count]"`` → (verb, seconds, count_or_None)."""
  parts = spec.split(":")
  if len(parts) not in (2, 3) or not parts[0]:
    raise ValueError(spec)
  return (parts[0], float(parts[1]),
          int(parts[2]) if len(parts) == 3 else None)


def _parse_action_spec(spec: str, hard_action: str):
  """``"point[@index][#nth]:<hard_action>"`` / ``"...:stall:seconds"`` →
  ((name, index, nth), action, seconds_or_None). The shared grammar
  behind the serve (``raise``) and fleet (``kill``) knobs."""
  parts = spec.split(":")
  if len(parts) < 2 or not parts[0]:
    raise ValueError(spec)
  target = _parse_point_spec(parts[0])
  action = parts[1]
  if action == hard_action:
    if len(parts) != 2:
      raise ValueError(spec)
    return target, action, None
  if action == "stall":
    if len(parts) != 3:
      raise ValueError(spec)
    return target, action, float(parts[2])
  raise ValueError(spec)


def _parse_serve_spec(spec: str):
  """``"point[@index][#nth]:raise"`` / ``"...:stall:seconds"``."""
  return _parse_action_spec(spec, "raise")


def _parse_fleet_spec(spec: str):
  """``"point[@replica][#nth]:kill"`` / ``"...:stall:seconds"``."""
  return _parse_action_spec(spec, "kill")


def _parse_deploy_spec(spec: str):
  """``"point[@index][#nth]:kill"``, ``"...:poison"`` or
  ``"...:stall:seconds"`` → ((name, index, nth), action, secs_or_None).
  The fleet grammar with TWO hard actions: ``kill`` (the controller dies
  at the boundary) and ``poison`` (the candidate's params are corrupted
  at the canary build)."""
  parts = spec.split(":")
  if len(parts) < 2 or not parts[0]:
    raise ValueError(spec)
  target = _parse_point_spec(parts[0])
  action = parts[1]
  if action in ("kill", "poison"):
    if len(parts) != 2:
      raise ValueError(spec)
    return target, action, None
  if action == "stall":
    if len(parts) != 3:
      raise ValueError(spec)
    return target, action, float(parts[2])
  raise ValueError(spec)


def _parse_host_spec(spec: str):
  """``"point[@host][#nth]:kill"``, ``"...:stall:seconds"`` or
  ``"...:partition:seconds"`` → ((name, host, nth), action,
  secs_or_None). The deploy grammar shape with a timed second hard
  action: ``partition`` carries a duration (how long the host's wire
  goes dark) but is NOT an inline stall — the caller keeps decoding."""
  parts = spec.split(":")
  if len(parts) < 2 or not parts[0]:
    raise ValueError(spec)
  target = _parse_point_spec(parts[0])
  action = parts[1]
  if action == "kill":
    if len(parts) != 2:
      raise ValueError(spec)
    return target, action, None
  if action in ("stall", "partition"):
    if len(parts) != 3:
      raise ValueError(spec)
    return target, action, float(parts[2])
  raise ValueError(spec)


def _parse_group_spec(spec: str):
  """``"kill[@group][#nth]"`` / ``"stall[@group][#nth]:seconds"`` →
  ((action, group_or_None, nth), seconds_or_None). The action leads (there
  is only one injection point — the sync-round boundary — so no point name
  to parse), reusing the ``@index``/``#nth`` suffix grammar."""
  parts = spec.split(":")
  target = _parse_point_spec(parts[0])
  action = target[0]
  if action == "kill":
    if len(parts) != 1:
      raise ValueError(spec)
    return target, None
  if action == "stall":
    if len(parts) != 2:
      raise ValueError(spec)
    return target, float(parts[1])
  raise ValueError(spec)


def _sentinel_path(name: str, index) -> str:
  safe = re.sub(r"[^A-Za-z0-9_.-]", "_", "%s_%s" % (name, index))
  return os.path.join(os.getcwd(), ".tos_chaos_fired_%s" % safe)


def kill_point(name: str, index: Optional[int] = None) -> None:
  """Deterministic crash site: SIGKILL this process when armed.

  Call sites name a point (e.g. ``"train-step"``) and pass their identity
  (executor id) as ``index``; the ``TOS_CHAOS_KILL`` spec decides whether
  and on which invocation the kill fires. SIGKILL — not an exception — so
  the process dies exactly the way a preempted/OOM-killed host does: no
  traceback, no cleanup, heartbeats just stop.
  """
  _first_consult()
  spec_env = os.environ.get(ENV_KILL)
  if not spec_env:
    return
  check_config()
  with _lock:
    count = _counts[(name, index)] = _counts.get((name, index), 0) + 1
  for spec in spec_env.split(","):
    sname, sindex, nth = _parse_point_spec(spec.strip())
    if sname != name or (sindex is not None and sindex != index):
      continue
    if count != nth:
      continue
    sentinel = _sentinel_path(name, index)
    if os.path.exists(sentinel):
      return  # already fired in a previous incarnation of this node
    with open(sentinel, "w") as f:
      f.write("fired at count %d pid %d\n" % (count, os.getpid()))
      f.flush()
      os.fsync(f.fileno())
    logger.warning("chaos: SIGKILL at point %r index %r (invocation %d)",
                   name, index, count)
    os.kill(os.getpid(), signal.SIGKILL)


def stall_point(name: str, index: Optional[int] = None) -> float:
  """Deterministic stall site: sleep when armed (first matching call per
  process). Returns the seconds slept (0.0 when disarmed)."""
  _first_consult()
  spec_env = os.environ.get(ENV_STALL)
  if not spec_env:
    return 0.0
  check_config()
  for spec in spec_env.split(","):
    (sname, sindex, _), duration = _parse_stall_spec(spec.strip())
    if sname != name or (sindex is not None and sindex != index):
      continue
    key = (name, index, "stall")
    with _lock:
      if key in _stalled:
        return 0.0
      _stalled.add(key)
    logger.warning("chaos: stalling %.2fs at point %r index %r",
                   duration, name, index)
    time.sleep(duration)
    return duration
  return 0.0


def serve_fault(name: str, index: Optional[int] = None) -> None:
  """Deterministic serving-plane fault site (``serving.slots`` arms
  ``prefill``/``decode``): raise :class:`InjectedFault` or stall when a
  ``TOS_CHAOS_SERVE`` spec matches this invocation.

  Two invocation counters run per point: a GLOBAL one (specs without
  ``@index``: "the nth time this point fires at all") and a per-index
  one (specs with ``@index``: "the nth time it fires for THIS index").
  The ``prefill`` point passes the prompt length as its index — the only
  stable identity a spec can name before request ids are assigned — so a
  per-index spec turns one crafted prompt into a deterministic poison
  request (docs/ROBUSTNESS.md).
  """
  _first_consult()
  spec_env = os.environ.get(ENV_SERVE)
  if not spec_env:
    return
  check_config()
  point = "serve." + name
  with _lock:
    gcount = _counts[(point, None)] = _counts.get((point, None), 0) + 1
    icount = gcount
    if index is not None:
      icount = _counts[(point, index)] = \
          _counts.get((point, index), 0) + 1
  for spec in _split_specs(spec_env):
    (sname, sindex, nth), action, secs = _parse_serve_spec(spec)
    if sname != name:
      continue
    if sindex is None:
      if gcount != nth:
        continue
    elif sindex != index or icount != nth:
      continue
    if action == "stall":
      logger.warning("chaos: stalling %.2fs at serving point %r index %r "
                     "(occurrence %d)", secs, name, index, nth)
      time.sleep(secs)
      continue
    logger.warning("chaos: raising at serving point %r index %r "
                   "(occurrence %d)", name, index, nth)
    raise InjectedFault(
        "chaos: injected fault at serving point %r (occurrence %d)"
        % (name, nth))


def fleet_fault(name: str, index: Optional[int] = None) -> Optional[str]:
  """Deterministic fleet-plane fault site (``serving.fleet`` arms
  ``dispatch`` with the target replica id as ``index``): returns
  ``"kill"`` when a ``TOS_CHAOS_FLEET`` kill spec matches this
  invocation — the CALLER then terminally kills that replica (the fault
  target is a replica, not the calling thread, so this hook signals
  instead of raising). Stall specs sleep inline (a slow dispatch hop)
  and return None, as does a disarmed/unmatched consult.

  Counters mirror :func:`serve_fault`: a GLOBAL per-point count (specs
  without ``@replica``: "the nth dispatch overall") and a per-index one
  (specs with it: "the nth dispatch routed to THIS replica").
  """
  _first_consult()
  spec_env = os.environ.get(ENV_FLEET)
  if not spec_env:
    return None
  check_config()
  point = "fleet." + name
  with _lock:
    gcount = _counts[(point, None)] = _counts.get((point, None), 0) + 1
    icount = gcount
    if index is not None:
      icount = _counts[(point, index)] = \
          _counts.get((point, index), 0) + 1
  for spec in _split_specs(spec_env):
    (sname, sindex, nth), action, secs = _parse_fleet_spec(spec)
    if sname != name:
      continue
    if sindex is None:
      if gcount != nth:
        continue
    elif sindex != index or icount != nth:
      continue
    if action == "stall":
      logger.warning("chaos: stalling %.2fs at fleet point %r replica %r "
                     "(occurrence %d)", secs, name, index, nth)
      time.sleep(secs)
      continue
    logger.warning("chaos: kill verdict at fleet point %r replica %r "
                   "(occurrence %d)", name, index, nth)
    return "kill"
  return None


def deploy_fault(name: str, index: Optional[int] = None) -> Optional[str]:
  """Deterministic deployment-plane fault site (``serving.deploy`` arms
  ``canary``/``verify``/``promote``/``rollback``): returns ``"kill"``
  when a ``TOS_CHAOS_DEPLOY`` kill spec matches this invocation — the
  CALLER then dies as the driver-side controller at that state-machine
  boundary (mid-promote is the headline: recovery must converge every
  replica to ONE version with zero shed) — or ``"poison"`` (the caller
  corrupts the candidate's params, the bad publish VERIFY must catch).
  Stall specs sleep inline and return None, as does a disarmed or
  unmatched consult.

  Counters mirror :func:`fleet_fault`: a GLOBAL per-point count (specs
  without ``@index``) and a per-index one (``promote`` passes the
  replica id being swapped; ``canary``/``verify``/``rollback`` pass the
  candidate version).
  """
  _first_consult()
  spec_env = os.environ.get(ENV_DEPLOY)
  if not spec_env:
    return None
  check_config()
  point = "deploy." + name
  with _lock:
    gcount = _counts[(point, None)] = _counts.get((point, None), 0) + 1
    icount = gcount
    if index is not None:
      icount = _counts[(point, index)] = \
          _counts.get((point, index), 0) + 1
  for spec in _split_specs(spec_env):
    (sname, sindex, nth), action, secs = _parse_deploy_spec(spec)
    if sname != name:
      continue
    if sindex is None:
      if gcount != nth:
        continue
    elif sindex != index or icount != nth:
      continue
    if action == "stall":
      logger.warning("chaos: stalling %.2fs at deploy point %r index %r "
                     "(occurrence %d)", secs, name, index, nth)
      time.sleep(secs)
      continue
    logger.warning("chaos: %s verdict at deploy point %r index %r "
                   "(occurrence %d)", action, name, index, nth)
    return action
  return None


def host_fault(name: str, index: Optional[int] = None):
  """Deterministic serving-host fault site (``serving.host`` consults
  ``sync`` at each sync-round boundary with the host id as ``index``):
  returns ``("kill", None)`` when a ``TOS_CHAOS_HOST`` kill spec
  matches this invocation — the CALLER then SIGKILLs its own process
  (the whole executor dies the way a preempted host does: no cleanup,
  the wire just goes silent) — or ``("partition", seconds)``: the
  caller skips all wire I/O for that long while its engine keeps
  decoding (a network partition, not a death). Stall specs sleep
  inline (a slow host) and return None, as does a disarmed/unmatched
  consult.

  Counters mirror :func:`fleet_fault`: a GLOBAL per-point count (specs
  without ``@host``) and a per-host one (specs with it: "this host's
  nth sync round").
  """
  _first_consult()
  spec_env = os.environ.get(ENV_HOST)
  if not spec_env:
    return None
  check_config()
  point = "host." + name
  with _lock:
    gcount = _counts[(point, None)] = _counts.get((point, None), 0) + 1
    icount = gcount
    if index is not None:
      icount = _counts[(point, index)] = \
          _counts.get((point, index), 0) + 1
  for spec in _split_specs(spec_env):
    (sname, sindex, nth), action, secs = _parse_host_spec(spec)
    if sname != name:
      continue
    if sindex is None:
      if gcount != nth:
        continue
    elif sindex != index or icount != nth:
      continue
    if action == "stall":
      logger.warning("chaos: stalling %.2fs at host point %r host %r "
                     "(occurrence %d)", secs, name, index, nth)
      time.sleep(secs)
      continue
    logger.warning("chaos: %s verdict at host point %r host %r "
                   "(occurrence %d)", action, name, index, nth)
    return action, secs
  return None


def group_fault(index: Optional[int] = None) -> Optional[str]:
  """Deterministic training-group fault site (``parallel.groups`` consults
  at each sync-round boundary with the group id as ``index``): returns
  ``"kill"`` when a ``TOS_CHAOS_GROUP`` kill spec matches this invocation
  — the CALLER then stops that whole mesh group without contributing to
  the round (the fault target is a group of devices, not the calling
  thread, so this hook signals instead of raising — the fleet_fault
  convention). Stall specs sleep inline at the boundary (a slow or
  partitioned group, exercising the sync deadline) and return None, as
  does a disarmed/unmatched consult.

  Counters mirror :func:`fleet_fault`: a GLOBAL count over all boundary
  consults (specs without ``@group``) and a per-group one (specs with it:
  "this group's nth boundary").
  """
  _first_consult()
  spec_env = os.environ.get(ENV_GROUP)
  if not spec_env:
    return None
  check_config()
  point = "group.sync"
  with _lock:
    gcount = _counts[(point, None)] = _counts.get((point, None), 0) + 1
    icount = gcount
    if index is not None:
      icount = _counts[(point, index)] = \
          _counts.get((point, index), 0) + 1
  for spec in _split_specs(spec_env):
    (action, sindex, nth), secs = _parse_group_spec(spec)
    if sindex is None:
      if gcount != nth:
        continue
    elif sindex != index or icount != nth:
      continue
    if action == "stall":
      logger.warning("chaos: stalling %.2fs at sync boundary, group %r "
                     "(occurrence %d)", secs, index, nth)
      time.sleep(secs)
      continue
    logger.warning("chaos: kill verdict for training group %r "
                   "(occurrence %d)", index, nth)
    return "kill"
  return None


def message_fault(verb) -> Tuple[bool, float]:
  """(drop, delay_seconds) for a rendezvous message of the given verb.

  Consulted by ``rendezvous.Client`` before each send. A dropped message
  never reaches the wire — the receiver simply never sees it, exactly like
  a lost datagram — and the client proceeds as if it were sent.
  """
  _first_consult()
  drop_env = os.environ.get(ENV_RV_DROP)
  delay_env = os.environ.get(ENV_RV_DELAY)
  if not drop_env and not delay_env:
    return False, 0.0
  check_config()
  drop = False
  delay = 0.0
  if drop_env:
    for spec in drop_env.split(","):
      sverb, count = _parse_drop_spec(spec.strip())
      if sverb != verb:
        continue
      with _lock:
        seen = _rv_counts[(verb, "drop")] = \
            _rv_counts.get((verb, "drop"), 0) + 1
      if seen <= count:
        drop = True
  if delay_env:
    for spec in delay_env.split(","):
      dverb, secs, limit = _parse_delay_spec(spec.strip())
      if dverb != verb:
        continue
      with _lock:
        seen = _rv_counts[(verb, "delay")] = \
            _rv_counts.get((verb, "delay"), 0) + 1
      if limit is None or seen <= limit:
        delay = secs
  return drop, delay
