"""Deterministic fault injection for exercising the fault-tolerance runtime.

On TPU pods, preemption and single-host failure are the common case, not the
exception — so every recovery path (missed-beat detection, supervised node
relaunch, checkpoint resume, feed requeue) must be exercisable by ordinary
CPU tests. This module provides the injection points; the recovery machinery
lives in ``control.rendezvous`` (liveness), ``cluster.ClusterSupervisor``
(relaunch) and ``engine.local`` (executor respawn).

Faults are armed via environment variables, so they flow naturally into
engine executor processes (``LocalEngine(env=...)``, Spark executor env)
and every child they spawn. All triggers are DETERMINISTIC: named injection
points fire on exact invocation counts, never at random.

Env vars (all optional; absent ⇒ every hook is a no-op):

``TOS_CHAOS_KILL`` = ``"point[@index][#nth]"`` (comma-separated specs)
    SIGKILL the calling process the nth time (default: 1st) the named
    :func:`kill_point` fires with a matching index. Example:
    ``"train-step@0#3"`` kills executor 0 the 3rd time it reaches the
    ``train-step`` point — i.e. *kill node N at step S*. Exactly-once
    across process restarts: a sentinel file in the working directory
    records the fire, so a relaunched node sails past the same point.

``TOS_CHAOS_STALL`` = ``"point[@index]:seconds"`` (comma-separated)
    Sleep at the named :func:`stall_point` (first matching call per
    process) — e.g. ``"feeder@1:3"`` stalls executor 1's feed task.

``TOS_CHAOS_RV_DROP`` = ``"VERB:count"`` (comma-separated)
    Client-side rendezvous fault: silently drop the first ``count``
    messages of the given verb before they hit the wire — e.g.
    ``"BEAT:3"`` makes the server miss three heartbeats.

``TOS_CHAOS_RV_DELAY`` = ``"VERB:seconds[:count]"`` (comma-separated)
    Client-side rendezvous fault: delay messages of the given verb by
    ``seconds`` before sending (first ``count`` messages; default: all).
"""

import logging
import os
import re
import signal
import threading
import time
from typing import Optional, Tuple

logger = logging.getLogger(__name__)

ENV_KILL = "TOS_CHAOS_KILL"
ENV_STALL = "TOS_CHAOS_STALL"
ENV_RV_DROP = "TOS_CHAOS_RV_DROP"
ENV_RV_DELAY = "TOS_CHAOS_RV_DELAY"

# per-process invocation counters, keyed by (point, index)
_counts = {}
_stalled = set()
_rv_counts = {}
_lock = threading.Lock()


def enabled() -> bool:
  """True when any chaos env var is armed (cheap fast-path guard)."""
  return any(os.environ.get(k) for k in
             (ENV_KILL, ENV_STALL, ENV_RV_DROP, ENV_RV_DELAY))


def reset() -> None:
  """Forget per-process counters (test isolation helper)."""
  with _lock:
    _counts.clear()
    _stalled.clear()
    _rv_counts.clear()


def _parse_point_spec(spec: str):
  """``"name[@index][#nth]"`` → (name, index_or_None, nth)."""
  nth = 1
  if "#" in spec:
    spec, n = spec.rsplit("#", 1)
    nth = int(n)
  index = None
  if "@" in spec:
    spec, i = spec.rsplit("@", 1)
    index = int(i)
  return spec, index, nth


def _sentinel_path(name: str, index) -> str:
  safe = re.sub(r"[^A-Za-z0-9_.-]", "_", "%s_%s" % (name, index))
  return os.path.join(os.getcwd(), ".tos_chaos_fired_%s" % safe)


def kill_point(name: str, index: Optional[int] = None) -> None:
  """Deterministic crash site: SIGKILL this process when armed.

  Call sites name a point (e.g. ``"train-step"``) and pass their identity
  (executor id) as ``index``; the ``TOS_CHAOS_KILL`` spec decides whether
  and on which invocation the kill fires. SIGKILL — not an exception — so
  the process dies exactly the way a preempted/OOM-killed host does: no
  traceback, no cleanup, heartbeats just stop.
  """
  spec_env = os.environ.get(ENV_KILL)
  if not spec_env:
    return
  with _lock:
    count = _counts[(name, index)] = _counts.get((name, index), 0) + 1
  for spec in spec_env.split(","):
    sname, sindex, nth = _parse_point_spec(spec.strip())
    if sname != name or (sindex is not None and sindex != index):
      continue
    if count != nth:
      continue
    sentinel = _sentinel_path(name, index)
    if os.path.exists(sentinel):
      return  # already fired in a previous incarnation of this node
    with open(sentinel, "w") as f:
      f.write("fired at count %d pid %d\n" % (count, os.getpid()))
      f.flush()
      os.fsync(f.fileno())
    logger.warning("chaos: SIGKILL at point %r index %r (invocation %d)",
                   name, index, count)
    os.kill(os.getpid(), signal.SIGKILL)


def stall_point(name: str, index: Optional[int] = None) -> float:
  """Deterministic stall site: sleep when armed (first matching call per
  process). Returns the seconds slept (0.0 when disarmed)."""
  spec_env = os.environ.get(ENV_STALL)
  if not spec_env:
    return 0.0
  for spec in spec_env.split(","):
    spec = spec.strip()
    if ":" not in spec:
      continue
    target, secs = spec.rsplit(":", 1)
    sname, sindex, _ = _parse_point_spec(target)
    if sname != name or (sindex is not None and sindex != index):
      continue
    key = (name, index, "stall")
    with _lock:
      if key in _stalled:
        return 0.0
      _stalled.add(key)
    duration = float(secs)
    logger.warning("chaos: stalling %.2fs at point %r index %r",
                   duration, name, index)
    time.sleep(duration)
    return duration
  return 0.0


def message_fault(verb) -> Tuple[bool, float]:
  """(drop, delay_seconds) for a rendezvous message of the given verb.

  Consulted by ``rendezvous.Client`` before each send. A dropped message
  never reaches the wire — the receiver simply never sees it, exactly like
  a lost datagram — and the client proceeds as if it were sent.
  """
  drop_env = os.environ.get(ENV_RV_DROP)
  delay_env = os.environ.get(ENV_RV_DELAY)
  if not drop_env and not delay_env:
    return False, 0.0
  drop = False
  delay = 0.0
  if drop_env:
    for spec in drop_env.split(","):
      if ":" not in spec:
        continue
      sverb, count = spec.strip().split(":", 1)
      if sverb != verb:
        continue
      with _lock:
        seen = _rv_counts[(verb, "drop")] = \
            _rv_counts.get((verb, "drop"), 0) + 1
      if seen <= int(count):
        drop = True
  if delay_env:
    for spec in delay_env.split(","):
      parts = spec.strip().split(":")
      if len(parts) < 2 or parts[0] != verb:
        continue
      limit = int(parts[2]) if len(parts) > 2 else None
      with _lock:
        seen = _rv_counts[(verb, "delay")] = \
            _rv_counts.get((verb, "delay"), 0) + 1
      if limit is None or seen <= limit:
        delay = float(parts[1])
  return drop, delay
