"""tensorflowonspark_tpu: a TPU-native distributed ML orchestration framework.

A brand-new, TPU-first framework with the capabilities of yahoo/TensorFlowOnSpark:
it lets a data-engine driver (Spark, or the built-in multi-process LocalEngine)
orchestrate distributed JAX/XLA training and inference on TPU pod slices.

Layer map (mirrors the capability surface of the reference, re-designed for TPU;
see SURVEY.md for the reference analysis):

- ``utils/``    L0': host/TPU platform utilities (replaces gpu_info.py/util.py/compat.py)
- ``control/``  L1': rendezvous control plane + per-host feed hub
                (replaces reservation.py/TFManager.py/marker.py; msgpack-over-TCP,
                not pickle)
- ``node.py``   L2': per-executor node runtime (replaces TFSparkNode.py)
- ``cluster.py``L3': cluster lifecycle API (replaces TFCluster.py)
- ``datafeed.py`` L4': in-main-fn user API (replaces TFNode.py DataFeed)
- ``pipeline.py`` L5': Estimator/Model ML pipeline (replaces pipeline.py)
- ``data/``     TFRecord codec + DataFrame interop (replaces dfutil.py + the
                tensorflow-hadoop jar + the Scala DFUtil layer)
- ``engine/``   executor-engine abstraction: Spark adapter + built-in LocalEngine
- ``parallel/`` TPU-native SPMD: meshes, shardings (dp/tp/pp/sp), collectives,
                ring attention — capabilities the reference delegated to
                tf.distribute, rebuilt on jax.sharding/pjit/shard_map
- ``models/``   flagship model families (MNIST, ResNet, U-Net, Transformer)
- ``ops/``      Pallas TPU kernels for hot ops
"""

import logging

# Library convention: never configure the root logger at import time (the
# reference called logging.basicConfig in its __init__ — deliberate there, but
# it hijacks the embedding application's logging). Driver entry points call
# setup_logging() to get the reference's thread/process-annotated format.
logging.getLogger(__name__).addHandler(logging.NullHandler())


def setup_logging(level=logging.INFO):
  """Opt-in logging setup with per-thread/process annotations.

  Format parity with the reference package init
  (/root/reference/tensorflowonspark/__init__.py:3).
  """
  logging.basicConfig(
      level=level,
      format="%(asctime)s %(levelname)s (%(threadName)s-%(process)d) "
             "%(message)s")


__version__ = "0.1.0"
