#!/bin/bash
# Install the framework + JAX TPU runtime on a fresh TPU VM.
# TPU-native analog of the reference's install_spark.sh (JDK + Spark
# download): here the "runtime" is jax[tpu] against libtpu, and the
# framework installs from this repo.
#
# Usage: ./install_tpu_vm.sh [repo-dir]
# Env:   PYTHON (default python3), TOS_EXTRAS (pip extras, default none)
set -euo pipefail

REPO_DIR="${1:-$(cd "$(dirname "$0")/.." && pwd)}"
PYTHON="${PYTHON:-python3}"

echo "== installing JAX TPU runtime =="
"$PYTHON" -m pip install -U pip
"$PYTHON" -m pip install -U "jax[tpu]" \
  -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

echo "== installing framework from ${REPO_DIR} =="
"$PYTHON" -m pip install -e "${REPO_DIR}${TOS_EXTRAS:+[$TOS_EXTRAS]}"

echo "== building the native codecs (optional, pure-Python fallback exists) =="
if command -v g++ >/dev/null 2>&1 && [ -d "${REPO_DIR}/native" ]; then
  make -C "${REPO_DIR}" native || \
    echo "native build failed; the pure-Python codec paths will be used"
fi

echo "== smoke test =="
"$PYTHON" - <<'EOF'
import jax
print("devices:", jax.devices())
import tensorflowonspark_tpu
print("framework import ok")
EOF
