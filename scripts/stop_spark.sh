#!/bin/bash
# Stop the Spark standalone cluster started by start_spark.sh
# (parity: reference scripts/stop_spark.sh).
set -euo pipefail
: "${SPARK_HOME:?set SPARK_HOME to a Spark installation}"
"${SPARK_HOME}/sbin/stop-worker.sh"
"${SPARK_HOME}/sbin/stop-master.sh"
