#!/bin/bash
# Start a Spark standalone cluster on this TPU VM, sized so each Spark
# worker slot owns one TPU-chip group. Parity with the reference's
# scripts/start_spark.sh (master + worker bring-up), with the worker
# count derived from the TPU topology instead of hand-set.
#
# Usage: ./start_spark.sh
# Env:   SPARK_HOME (required), CHIPS_PER_NODE (default 1),
#        SPARK_WORKER_MEM (default 4G)
set -euo pipefail

: "${SPARK_HOME:?set SPARK_HOME to a Spark installation}"
CHIPS_PER_NODE="${CHIPS_PER_NODE:-1}"
SPARK_WORKER_MEM="${SPARK_WORKER_MEM:-4G}"

# chips on this host -> number of worker slots
CHIPS=$(python3 - <<'EOF'
from tensorflowonspark_tpu.utils import tpu_info
topo = tpu_info.get_topology()
print(topo.chips_per_host if topo else 0)
EOF
)
if [ "${CHIPS}" = "0" ]; then
  echo "no TPU topology visible; defaulting to 1 worker slot" >&2
  CHIPS=1
fi
WORKERS=$(( CHIPS / CHIPS_PER_NODE ))
[ "${WORKERS}" -ge 1 ] || WORKERS=1

export MASTER="spark://$(hostname):7077"
export SPARK_WORKER_INSTANCES="${WORKERS}"

echo "== starting master (${MASTER}) + ${WORKERS} worker slot(s) =="
"${SPARK_HOME}/sbin/start-master.sh"
"${SPARK_HOME}/sbin/start-worker.sh" -c 1 -m "${SPARK_WORKER_MEM}" "${MASTER}"

echo "export MASTER=${MASTER}"
echo "export SPARK_WORKER_INSTANCES=${WORKERS}"
echo "submit with: scripts/submit_train.sh <app.py> [args...]"
