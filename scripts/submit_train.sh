#!/bin/bash
# spark-submit wrapper that plumbs the framework's TPU environment into
# every executor — the incantation the reference documented per-example
# (its README spark-submit blocks), packaged once.
#
# Usage: ./submit_train.sh <app.py> [app args...]
# Env:   MASTER (default spark://$(hostname):7077),
#        SPARK_WORKER_INSTANCES (default 2), CHIPS_PER_NODE (default 1),
#        TOS_TPU_SERVER_HOST/PORT (optional control-plane pinning),
#        EXTRA_SPARK_CONF (optional, e.g. "--conf spark.speculation=true")
set -euo pipefail

[ $# -ge 1 ] || { echo "usage: $0 <app.py> [args...]" >&2; exit 2; }
APP="$1"; shift

MASTER="${MASTER:-spark://$(hostname):7077}"
WORKERS="${SPARK_WORKER_INSTANCES:-2}"
CHIPS_PER_NODE="${CHIPS_PER_NODE:-1}"

# executor env: TPU placement + optional control-plane pinning. The
# framework's pipeline/transform tasks claim disjoint chip groups
# themselves (pipeline._allocate_transform_chips); cluster.run carves
# chips via chips_per_node at reservation time.
ENV_CONF=(
  --conf "spark.executorEnv.TFOS_TPU_FLASH_BWD=${TFOS_TPU_FLASH_BWD:-fused}"
)
[ -n "${TOS_TPU_SERVER_HOST:-}" ] && ENV_CONF+=(
  --conf "spark.executorEnv.TOS_TPU_SERVER_HOST=${TOS_TPU_SERVER_HOST}")
[ -n "${TOS_TPU_SERVER_PORT:-}" ] && ENV_CONF+=(
  --conf "spark.executorEnv.TOS_TPU_SERVER_PORT=${TOS_TPU_SERVER_PORT}")

exec "${SPARK_HOME}/bin/spark-submit" \
  --master "${MASTER}" \
  --deploy-mode client \
  --num-executors "${WORKERS}" \
  --executor-cores 1 \
  --conf spark.task.maxFailures=4 \
  --conf spark.dynamicAllocation.enabled=false \
  "${ENV_CONF[@]}" \
  ${EXTRA_SPARK_CONF:-} \
  "${APP}" \
  --cluster_size "${WORKERS}" \
  --chips_per_node "${CHIPS_PER_NODE}" \
  "$@"
