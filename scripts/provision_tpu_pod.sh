#!/bin/bash
# Create a TPU pod slice (multi-host) and install the framework on every
# host. TPU-native analog of the reference's spark-ec2 provisioning
# (scripts/spark_ec2.py): cloud resources in, ready-to-train cluster out.
#
# Usage: ./provision_tpu_pod.sh
# Env:   TPU_NAME (default tos-pod), ZONE (default us-central2-b),
#        ACCELERATOR (default v4-32), RUNTIME_VERSION (default
#        tpu-ubuntu2204-base), REPO_GIT (default: rsync this checkout)
set -euo pipefail

TPU_NAME="${TPU_NAME:-tos-pod}"
ZONE="${ZONE:-us-central2-b}"
ACCELERATOR="${ACCELERATOR:-v4-32}"
RUNTIME_VERSION="${RUNTIME_VERSION:-tpu-ubuntu2204-base}"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"

echo "== creating TPU pod slice ${TPU_NAME} (${ACCELERATOR}) =="
gcloud compute tpus tpu-vm create "${TPU_NAME}" \
  --zone="${ZONE}" \
  --accelerator-type="${ACCELERATOR}" \
  --version="${RUNTIME_VERSION}"

echo "== shipping the framework to every host =="
if [ -n "${REPO_GIT:-}" ]; then
  gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone="${ZONE}" --worker=all \
    --command="git clone --depth 1 ${REPO_GIT} tensorflowonspark_tpu || (cd tensorflowonspark_tpu && git pull)"
else
  gcloud compute tpus tpu-vm scp --recurse "${REPO_DIR}" \
    "${TPU_NAME}:~/tensorflowonspark_tpu" --zone="${ZONE}" --worker=all
fi

echo "== installing on every host =="
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone="${ZONE}" --worker=all \
  --command="bash ~/tensorflowonspark_tpu/scripts/install_tpu_vm.sh ~/tensorflowonspark_tpu"

cat <<EOF
== pod ready ==
Run a multi-host job (one process per host; JAX wires the ICI mesh):
  gcloud compute tpus tpu-vm ssh ${TPU_NAME} --zone=${ZONE} --worker=all \\
    --command="cd ~/tensorflowonspark_tpu && python examples/mnist/mnist_engine.py ..."
Point executors at a remote driver's control plane with
  TOS_TPU_SERVER_HOST=<driver-ip> TOS_TPU_SERVER_PORT=<port>
(see scripts/README.md for the full env checklist).
EOF
