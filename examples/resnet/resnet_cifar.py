"""ResNet-56 on CIFAR-sized data, distributed over cluster nodes.

Parity with the reference's ``examples/resnet/resnet_cifar_dist.py``
(ResNet-56 CIFAR under a tf.distribute strategy chosen by flag): each node
trains the flax ResNet on its shard; with real TPU chips, pass
``--chips_per_node`` so co-located nodes split the host's chips.

Run:  python examples/resnet/resnet_cifar.py --executors 2 --steps 30
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import numpy as np
  import jax
  import jax.numpy as jnp
  from tensorflowonspark_tpu.models import resnet

  rng = np.random.RandomState(ctx.executor_id)
  images = rng.rand(args.num_samples, 32, 32, 3).astype("float32")
  labels = rng.randint(0, 10, args.num_samples).astype("int32")

  model = resnet.ResNet56CIFAR()
  state = resnet.create_state(jax.random.PRNGKey(0), model,
                              image_shape=(32, 32, 3),
                              learning_rate=args.lr)
  bs = args.batch_size
  for step in range(args.steps):
    lo = (step * bs) % max(1, args.num_samples - bs + 1)
    state, loss = resnet.train_step(state, jnp.asarray(images[lo:lo + bs]),
                                    jnp.asarray(labels[lo:lo + bs]))
    if step % 10 == 0:
      print("node %d step %d loss %.4f"
            % (ctx.executor_id, step, float(loss)))
  if ctx.is_chief and args.export_dir:
    ctx.export_model(jax.device_get(state.params), args.export_dir)


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--steps", type=int, default=30)
  parser.add_argument("--batch_size", type=int, default=128)
  parser.add_argument("--num_samples", type=int, default=1024)
  parser.add_argument("--lr", type=float, default=0.05)
  parser.add_argument("--chips_per_node", type=int, default=0)
  parser.add_argument("--export_dir", default=None)
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.FILES,
                    chips_per_node=args.chips_per_node)
    c.shutdown()
    print("resnet training complete")
  finally:
    engine.stop()
