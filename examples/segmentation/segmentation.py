"""U-Net segmentation, multi-worker.

Parity with the reference's ``examples/segmentation/segmentation_spark.py``
(MobileNetV2-U-Net multi-worker training): each node trains the flax U-Net
on its synthetic shard and the chief exports the bundle.

Run:  python examples/segmentation/segmentation.py --executors 2 --steps 20
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import jax
  import jax.numpy as jnp
  from tensorflowonspark_tpu.models import segmentation as seg

  images, masks = seg.synthetic_dataset(args.num_samples, size=args.size,
                                        seed=ctx.executor_id)
  state = seg.create_state(jax.random.PRNGKey(0),
                           model=seg.UNet(encoder_filters=(16, 32, 64)),
                           image_shape=(args.size, args.size, 3))
  bs = args.batch_size
  for step in range(args.steps):
    lo = (step * bs) % max(1, args.num_samples - bs + 1)
    state, loss = seg.train_step(state, jnp.asarray(images[lo:lo + bs]),
                                 jnp.asarray(masks[lo:lo + bs]))
    if step % 5 == 0:
      print("node %d step %d loss %.4f"
            % (ctx.executor_id, step, float(loss)))
  if ctx.is_chief and args.export_dir:
    ctx.export_model(jax.device_get(state.params), args.export_dir)


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--steps", type=int, default=20)
  parser.add_argument("--batch_size", type=int, default=8)
  parser.add_argument("--num_samples", type=int, default=64)
  parser.add_argument("--size", type=int, default=64)
  parser.add_argument("--export_dir", default=None)
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.FILES)
    c.shutdown()
    print("segmentation training complete")
  finally:
    engine.stop()
