"""MNIST, ENGINE input mode: the engine pushes partitioned rows into each
node's DataFeed.

Parity with the reference's ``examples/mnist/keras/mnist_spark.py``
(InputMode.SPARK + DataFeed generator): rows stream through the feed hub
in chunks, the node assembles device batches, and the driver replays the
dataset for N epochs.

Run:  python examples/mnist/mnist_engine.py --executors 2 --epochs 2
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import jax
  import numpy as np
  from tensorflowonspark_tpu.models import mnist

  feed = ctx.get_data_feed(train_mode=True)
  state = mnist.create_state(jax.random.PRNGKey(args.seed))
  step = 0
  while not feed.should_stop():
    batch = feed.next_batch(args.batch_size)
    if not batch:
      continue
    images = np.asarray([b[0] for b in batch], "float32")
    labels = np.asarray([b[1] for b in batch], "int32")
    state, loss = mnist.train_step(state, images, labels)
    step += 1
    if step % 20 == 0:
      print("node %d step %d loss %.4f" % (ctx.executor_id, step,
                                           float(loss)))
  print("node %d done after %d steps" % (ctx.executor_id, step))
  if ctx.is_chief and args.export_dir:
    ctx.export_model(state.params, args.export_dir)


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--epochs", type=int, default=2)
  parser.add_argument("--batch_size", type=int, default=64)
  parser.add_argument("--num_samples", type=int, default=2048)
  parser.add_argument("--partitions", type=int, default=8)
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--export_dir", default=None)
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.models import mnist

  images, labels = mnist.synthetic_dataset(args.num_samples)
  rows = list(zip(images.tolist(), labels.tolist()))
  partitions = [rows[i::args.partitions] for i in range(args.partitions)]

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.ENGINE)
    c.train(partitions, num_epochs=args.epochs)
    c.shutdown(grace_secs=2)
    print("training complete")
  finally:
    engine.stop()
