"""MNIST, ENGINE input mode: the engine pushes partitioned rows into each
node's DataFeed.

Parity with the reference's ``examples/mnist/keras/mnist_spark.py``
(InputMode.SPARK + DataFeed generator): rows stream through the feed hub
in chunks, the node assembles device batches, and the driver replays the
dataset for N epochs.

Run:  python examples/mnist/mnist_engine.py --executors 2 --epochs 2
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import jax
  import numpy as np
  from tensorflowonspark_tpu.data.readers import device_prefetch, \
      slab_batches
  from tensorflowonspark_tpu.models import mnist
  from tensorflowonspark_tpu.parallel import mesh as mesh_lib
  from tensorflowonspark_tpu.parallel import sharding

  # columnar feed: batches (and train-loop slabs) assemble from column
  # views, no per-row python loop; sorted mapping keys follow row order
  feed = ctx.get_data_feed(
      train_mode=True, input_mapping={"c0_image": "image",
                                      "c1_label": "label"})
  model = mnist.MLP()
  state = mnist.create_state(jax.random.PRNGKey(args.seed), model=model)
  mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=-1),
                             devices=jax.devices()[:1])

  def loss_fn(params, batch):
    logits = model.apply({"params": params},
                         batch["image"].astype("float32"), train=True)
    return mnist.loss_fn(logits, batch["label"].astype("int32"))

  # unroll defaults to the cluster's train_unroll (TOS_TRAIN_UNROLL):
  # K steps fused into one dispatch, same trajectory as per-step
  loop = sharding.make_train_loop(loss_fn, mesh, donate_state=False)
  for item in device_prefetch(slab_batches(feed, args.batch_size),
                              size=2):
    state, losses = loop(state, item)
    if loop.steps % 20 < len(np.asarray(losses)):
      print("node %d step %d loss %.4f"
            % (ctx.executor_id, loop.steps, float(np.asarray(losses)[-1])))
  print("node %d done after %d steps (unroll=%d)"
        % (ctx.executor_id, loop.steps, loop.unroll))
  if ctx.is_chief and args.export_dir:
    ctx.export_model(state.params, args.export_dir)


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--epochs", type=int, default=2)
  parser.add_argument("--batch_size", type=int, default=64)
  parser.add_argument("--num_samples", type=int, default=2048)
  parser.add_argument("--partitions", type=int, default=8)
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--export_dir", default=None)
  parser.add_argument("--unroll", type=int, default=0,
                      help="fuse K optimizer steps per dispatch on every "
                           "node (cluster.run(train_unroll=K); 0 = "
                           "per-step)")
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.models import mnist

  images, labels = mnist.synthetic_dataset(args.num_samples)
  # ndarray image rows + exact-int labels keep the feed columnar end to
  # end (feeder encodes one column chunk; nodes assemble by column views)
  rows = list(zip(images, labels.tolist()))
  partitions = [rows[i::args.partitions] for i in range(args.partitions)]

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.ENGINE,
                    train_unroll=args.unroll or None)
    c.train(partitions, num_epochs=args.epochs)
    c.shutdown(grace_secs=2)
    print("training complete")
  finally:
    engine.stop()
