"""MNIST streaming training: unbounded micro-batch feed with graceful stop.

Parity with the reference's
``examples/mnist/estimator/mnist_spark_streaming.py`` (DStream feeding with
a stop_streaming signal): the driver feeds rounds from a stream source;
any process with the cluster's rendezvous address can stop it gracefully
(``rendezvous.Client(addr).request_stop()`` — the stop_streaming analog).

Run:  python examples/mnist/mnist_streaming.py --executors 2 --rounds 5
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import jax
  import numpy as np
  from tensorflowonspark_tpu.models import mnist

  feed = ctx.get_data_feed(train_mode=True)
  state = mnist.create_state(jax.random.PRNGKey(0))
  steps = 0
  while not feed.should_stop():
    batch = feed.next_batch(args.batch_size)
    if not batch:
      continue
    images = np.asarray([b[0] for b in batch], "float32")
    labels = np.asarray([b[1] for b in batch], "int32")
    state, loss = mnist.train_step(state, images, labels)
    steps += 1
  print("node %d processed %d streamed steps" % (ctx.executor_id, steps))


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--rounds", type=int, default=5,
                      help="rounds before the driver sends the stop signal")
  parser.add_argument("--batch_size", type=int, default=32)
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.control.rendezvous import Client
  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.models import mnist

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.ENGINE)

    def stream():
      round_no = 0
      while True:                      # unbounded source
        images, labels = mnist.synthetic_dataset(256, seed=round_no)
        rows = list(zip(images.tolist(), labels.tolist()))
        round_no += 1
        if round_no >= args.rounds:
          # signal BEFORE yielding the final round: train_stream feeds it,
          # sees the flag, and stops at exactly --rounds rounds.
          # (any process with the rendezvous address can do this)
          Client(tuple(c.server_addr)).request_stop()
        yield [rows[i::4] for i in range(4)]

    rounds = c.train_stream(stream(), feed_timeout=120)
    print("streamed %d rounds; shutting down" % rounds)
    c.shutdown(grace_secs=2)
  finally:
    engine.stop()
