"""MNIST, FILES input mode: every node reads/generates its own data shard.

Parity with the reference's ``examples/mnist/keras/mnist_tf.py`` (each
worker reads tfds itself under MultiWorkerMirroredStrategy) — here each
node trains the flax MLP on its shard; multi-node gradient sync comes from
``jax.distributed`` + data-parallel sharding when the cluster has >1 node.

Run:  python examples/mnist/mnist_files.py --executors 2 --steps 200
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import jax
  from tensorflowonspark_tpu.models import mnist

  images, labels = mnist.synthetic_dataset(
      args.num_samples, seed=ctx.executor_id)
  state = mnist.create_state(jax.random.PRNGKey(args.seed),
                             model=mnist.CNN() if args.model == "cnn"
                             else mnist.MLP())
  bs = args.batch_size
  for step in range(args.steps):
    lo = (step * bs) % max(1, len(images) - bs + 1)
    state, loss = mnist.train_step(state, images[lo:lo + bs],
                                   labels[lo:lo + bs])
    if step % 50 == 0:
      print("node %d step %d loss %.4f" % (ctx.executor_id, step,
                                           float(loss)))
  _, acc = mnist.eval_step(state, images, labels)
  print("node %d final accuracy %.3f" % (ctx.executor_id, float(acc)))
  if ctx.is_chief and args.export_dir:
    ctx.export_model(state.params, args.export_dir)


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--steps", type=int, default=200)
  parser.add_argument("--batch_size", type=int, default=64)
  parser.add_argument("--num_samples", type=int, default=2048)
  parser.add_argument("--model", choices=["mlp", "cnn"], default="mlp")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--export_dir", default=None)
  parser.add_argument("--tensorboard", action="store_true")
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.FILES,
                    tensorboard=args.tensorboard)
    c.shutdown()
    print("training complete; tensorboard:", c.tensorboard_url())
  finally:
    engine.stop()
