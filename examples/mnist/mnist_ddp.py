"""MNIST with TRUE distributed data parallelism.

The closest analog of the reference's MultiWorkerMirroredStrategy examples
(`examples/mnist/keras/mnist_spark.py`): the cluster synthesizes
jax.distributed coordinates from its rendezvous, every node joins ONE
process group, batches are globally sharded, and XLA inserts the gradient
all-reduce — so all nodes step in lockstep with identical parameters
(verify: both print the same loss curve).

Run:  python examples/mnist/mnist_ddp.py --executors 2 --steps 40
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import jax
  from jax.sharding import NamedSharding, PartitionSpec as P
  from tensorflowonspark_tpu.models import mnist

  ctx.initialize_distributed()
  mesh = jax.make_mesh((jax.device_count(),), ("data",))
  repl = NamedSharding(mesh, P())
  data_sharding = NamedSharding(mesh, P("data"))

  # identical initial params everywhere (same seed, replicated layout)
  state = jax.jit(lambda: mnist.create_state(jax.random.PRNGKey(0)),
                  out_shardings=repl)()
  images, labels = mnist.synthetic_dataset(args.num_samples,
                                           seed=ctx.process_id)
  bs = args.batch_size
  for step in range(args.steps):
    lo = (step * bs) % max(1, args.num_samples - bs + 1)
    gi = jax.make_array_from_process_local_data(
        data_sharding, images[lo:lo + bs])
    gl = jax.make_array_from_process_local_data(
        data_sharding, labels[lo:lo + bs])
    state, loss = mnist.train_step(state, gi, gl)
    if step % 10 == 0:
      print("node %d step %d loss %.4f (global batch %d)"
            % (ctx.executor_id, step, float(loss),
               bs * jax.process_count()))
  if ctx.is_chief and args.export_dir:
    ctx.export_model(jax.device_get(state.params), args.export_dir)


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--steps", type=int, default=40)
  parser.add_argument("--batch_size", type=int, default=64,
                      help="per-process batch; global = this x processes")
  parser.add_argument("--num_samples", type=int, default=2048)
  parser.add_argument("--export_dir", default=None)
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.FILES)
    c.shutdown()
    print("distributed training complete")
  finally:
    engine.stop()
