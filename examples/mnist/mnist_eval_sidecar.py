"""MNIST with an evaluator sidecar: train-and-evaluate via checkpoints.

Parity with the reference's Estimator ``train_and_evaluate`` topology
(its ``num_ps``/evaluator role template, TFCluster.py role assembly):
workers train and periodically checkpoint through
``utils.checkpoint.CheckpointManager``; the evaluator node polls the
checkpoint directory, restores each new step, and scores a held-out
shard — completely decoupled from the training feed. ``cluster.run``
places the evaluator via ``eval_node=True``; ``shutdown()`` ends it (the
node's parking loop consumes the driver's control-queue None and flips
the hub state off "running", which the sidecar polls).

Run:  python examples/mnist/mnist_eval_sidecar.py --executors 3
(2 workers + 1 evaluator; LocalEngine — swap in SparkEngine unchanged.)
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def main_fn(args, ctx):
  import os
  import time
  import jax
  import numpy as np
  from tensorflowonspark_tpu.models import mnist
  from tensorflowonspark_tpu.utils.checkpoint import CheckpointManager

  state = mnist.create_state(jax.random.PRNGKey(args.seed))

  if ctx.job_name == "evaluator":
    # sidecar: poll for new checkpoints, score the held-out shard
    # SAME seed as training: synthetic_dataset's class templates derive
    # from the seed, so a different seed is a different task entirely
    # (scores chance accuracy forever); same-seed draws share templates
    images, labels = mnist.synthetic_dataset(args.eval_samples,
                                             seed=args.seed)
    mgr = CheckpointManager(args.model_dir, save_interval_steps=1)
    seen = -1

    fails = {}

    def _eval(step_num):
      try:
        restored = mgr.restore(state, step=step_num)
      except Exception as e:   # noqa: BLE001 - usually still committing
        fails[step_num] = fails.get(step_num, 0) + 1
        if fails[step_num] in (4, 20):   # persistent: surface, rate-limited
          print("evaluator: restore of step %d failing repeatedly: %r"
                % (step_num, e), flush=True)
        return False
      loss, acc = mnist.eval_step(restored, images, labels)
      line = ("evaluator: step %d loss %.4f accuracy %.3f"
              % (step_num, float(loss), float(acc)))
      print(line, flush=True)
      with open(os.path.join(args.model_dir, "eval_log.txt"), "a") as f:
        f.write(line + "\n")
      return True

    while True:
      # the stop signal for a USER sidecar is the hub STATE flipping off
      # "running" (the node's own foreground loop owns the control queue
      # and consumes the driver's None); check-stop AFTER scoring so the
      # stop iteration still evaluates the final checkpoint
      stop = ctx.hub.get("state") != "running"
      latest = mgr.latest_step(refresh=True)
      if latest is not None and latest != seen and _eval(latest):
        seen = latest
      if stop:
        break
      time.sleep(0.5)
    print("evaluator: stop signal after step %d" % seen, flush=True)
    return

  # workers: train from the engine feed, chief checkpoints periodically
  feed = ctx.get_data_feed(train_mode=True)
  mgr = CheckpointManager(args.model_dir,
                          save_interval_steps=args.save_interval)
  step = 0
  while not feed.should_stop():
    batch = feed.next_batch(args.batch_size)
    if not batch:
      continue
    bx = np.asarray([b[0] for b in batch], "float32")
    by = np.asarray([b[1] for b in batch], "int32")
    state, loss = mnist.train_step(state, bx, by)
    step += 1
    mgr.save(step, state, is_chief=ctx.is_chief)
    if args.step_delay:
      time.sleep(args.step_delay)   # demo pacing: keep training alive
                                    # past the evaluator's cold start
  mgr.wait()
  print("worker %d done after %d steps" % (ctx.executor_id, step))


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=3)
  parser.add_argument("--batch_size", type=int, default=64)
  parser.add_argument("--num_samples", type=int, default=1024)
  parser.add_argument("--eval_samples", type=int, default=256)
  parser.add_argument("--partitions", type=int, default=4)
  parser.add_argument("--save_interval", type=int, default=5)
  parser.add_argument("--epochs", type=int, default=3)
  parser.add_argument("--step_delay", type=float, default=0.25)
  parser.add_argument("--model_dir", default="/tmp/mnist_eval_sidecar")
  parser.add_argument("--seed", type=int, default=0)
  args = parser.parse_args()

  from tensorflowonspark_tpu import cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.models import mnist as mnist_mod

  images, labels = mnist_mod.synthetic_dataset(args.num_samples,
                                               seed=args.seed)
  rows = list(zip(images, labels))
  k = args.partitions
  partitions = [rows[i::k] for i in range(k)]

  engine = LocalEngine(num_executors=args.executors)
  try:
    c = cluster.run(engine, main_fn, tf_args=args,
                    input_mode=InputMode.ENGINE, eval_node=True)
    c.train(partitions, num_epochs=args.epochs)
    c.shutdown(timeout=300)   # also stops the evaluator (hub state)
  finally:
    engine.stop()
