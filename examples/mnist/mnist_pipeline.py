"""MNIST via the Estimator/Model pipeline API.

Parity with the reference's ``examples/mnist/keras/mnist_pipeline.py``:
TFEstimator.fit trains on a cluster fed by the engine, exports a bundle,
and TFModel.transform runs batch inference per executor.

Run:  python examples/mnist/mnist_pipeline.py --executors 2
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def train_fn(args, ctx):
  import jax
  import numpy as np
  from tensorflowonspark_tpu import pipeline
  from tensorflowonspark_tpu.models import mnist

  feed = ctx.get_data_feed(train_mode=True,
                           input_mapping={"image": "x", "label": "y"})
  state = mnist.create_state(jax.random.PRNGKey(0))
  while not feed.should_stop():
    batch = feed.next_batch(args["batch_size"])
    if not batch["x"]:
      continue
    images = np.asarray(batch["x"], "float32")
    labels = np.asarray(batch["y"], "int32")
    state, _ = mnist.train_step(state, images, labels)

  if ctx.is_chief:
    apply_fn = state.apply_fn

    def predict_fn(params, batch):
      import numpy as np
      logits = apply_fn({"params": params},
                        np.asarray(batch["x"], "float32"))
      return {"label": np.argmax(np.asarray(logits), -1)}

    pipeline.export_bundle(jax.device_get(state.params), predict_fn,
                           args["export_dir"], is_chief=True)


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--export_dir", default="/tmp/mnist_export")
  parser.add_argument("--num_samples", type=int, default=2048)
  args = parser.parse_args()

  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.models import mnist
  from tensorflowonspark_tpu.pipeline import TFEstimator

  images, labels = mnist.synthetic_dataset(args.num_samples)
  rows = list(zip(images.tolist(), labels.tolist()))
  partitions = [rows[i::8] for i in range(8)]

  engine = LocalEngine(num_executors=args.executors)
  try:
    est = TFEstimator(train_fn, {"export_dir": args.export_dir,
                                 "batch_size": 64})
    est.setEpochs(3).setGraceSecs(2).setReservationTimeout(60)
    model = est.fit(engine, partitions)

    model.setExportDir(args.export_dir) \
         .setInputMapping({"image": "x"}) \
         .setOutputMapping({"label": "prediction"})
    test_rows = [(img,) for img, _ in rows[:256]]
    preds = model.transform(engine, [test_rows])
    truth = [lbl for _, lbl in rows[:256]]
    acc = sum(int(p == t) for p, t in zip(preds, truth)) / len(truth)
    print("pipeline inference accuracy: %.3f over %d rows" %
          (acc, len(truth)))
  finally:
    engine.stop()
