"""MNIST embarrassingly-parallel inference with the barrier runner.

Parity with the reference's ``examples/mnist/keras/mnist_inference.py``
(TFParallel.run): independent single-node instances, gang-scheduled, each
processing its own file shard — no cluster, no feed plane.

Run:  python examples/mnist/mnist_parallel_inference.py --executors 2
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()


def infer_fn(args, ctx):
  import jax
  from tensorflowonspark_tpu.models import mnist

  # each task scores its own shard (sharded by task id among gang size)
  n = max(1, len(ctx.cluster_spec.get("worker", [1])))
  images, labels = mnist.synthetic_dataset(args.num_samples,
                                           seed=args.seed)
  images, labels = images[ctx.task_index::n], labels[ctx.task_index::n]
  state = mnist.create_state(jax.random.PRNGKey(0))
  for _ in range(args.warm_steps):  # quick fit so predictions are sane
    state, _ = mnist.train_step(state, images[:64], labels[:64])
  _, acc = mnist.eval_step(state, images, labels)
  return {"task": ctx.task_index, "rows": int(len(images)),
          "accuracy": float(acc)}


if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--num_samples", type=int, default=1024)
  parser.add_argument("--warm_steps", type=int, default=60)
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--no_barrier", action="store_true")
  args = parser.parse_args()

  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.parallel import runner

  engine = LocalEngine(num_executors=args.executors)
  try:
    results = runner.run(engine, infer_fn, tf_args=args,
                         num_tasks=args.executors,
                         use_barrier=not args.no_barrier)
    for r in sorted(results, key=lambda r: r["task"]):
      print("task %d: %d rows, accuracy %.3f"
            % (r["task"], r["rows"], r["accuracy"]))
  finally:
    engine.stop()
