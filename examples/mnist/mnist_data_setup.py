"""Export the MNIST-like dataset to TFRecord files.

Parity with the reference's ``examples/mnist/mnist_data_setup.py``
(tfds → TFRecord export via Spark): writes partitioned TFRecord shards
through the native codec, which mnist_tfrecords-style jobs then read with
``data.readers`` (the environment has no dataset egress, so the images are
the deterministic synthetic set from models.mnist).

Run:  python examples/mnist/mnist_data_setup.py --output /tmp/mnist_tfr
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()

if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--output", default="/tmp/mnist_tfrecords")
  parser.add_argument("--num_samples", type=int, default=4096)
  parser.add_argument("--partitions", type=int, default=8)
  parser.add_argument("--executors", type=int, default=0,
                      help="write via engine executors when > 0")
  args = parser.parse_args()

  from tensorflowonspark_tpu.data import dfutil
  from tensorflowonspark_tpu.data.schema import parse_schema
  from tensorflowonspark_tpu.models import mnist

  images, labels = mnist.synthetic_dataset(args.num_samples)
  schema = parse_schema("struct<image:array<float>,label:long>")
  rows = [(img.reshape(-1).tolist(), int(lbl))
          for img, lbl in zip(images, labels)]
  parts = [rows[i::args.partitions] for i in range(args.partitions)]

  engine = None
  try:
    if args.executors:
      from tensorflowonspark_tpu.engine import LocalEngine
      engine = LocalEngine(num_executors=args.executors)
    files = dfutil.save_as_tfrecords(parts, schema, args.output,
                                     engine=engine)
    print("wrote %d shard(s) to %s" % (len(files), args.output))
  finally:
    if engine:
      engine.stop()
