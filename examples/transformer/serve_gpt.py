"""Serve a Transformer LM through the pipeline bundle path.

Exports a (toy) causal LM as a model bundle, then runs batched KV-cache
decoding over prompt partitions with ``TFModel.transform`` on real
executor processes — the serving analog of the reference's
batch-inference flow (Spark ML TFModel / Inference.scala), with
``collect=False`` streaming so the driver never holds the full output.

  python examples/transformer/serve_gpt.py --steps 8 --prompts 32
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()

if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--steps", type=int, default=8,
                      help="tokens to generate per prompt")
  parser.add_argument("--prompts", type=int, default=32)
  parser.add_argument("--prompt_len", type=int, default=8)
  parser.add_argument("--temperature", type=float, default=0.0)
  parser.add_argument("--export_dir", default="/tmp/tos_tpu_serve_gpt")
  parser.add_argument("--executors", type=int, default=2)
  parser.add_argument("--tensor", type=int, default=1,
                      help="tensor-parallel degree per executor: the "
                           "bundle carries a MeshSpec and each executor "
                           "builds its mesh from its own devices (heads "
                           "+ KV cache sharded, batch over data)")
  args = parser.parse_args()

  import numpy as np
  import jax
  from tensorflowonspark_tpu import pipeline
  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.models import transformer as tfm

  cfg = tfm.TransformerConfig(vocab_size=256, num_layers=2, num_heads=4,
                              num_kv_heads=2, d_model=128, d_ff=256,
                              max_seq_len=64, remat=False)
  state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=16)
  mesh_spec = None
  if args.tensor > 1:
    from tensorflowonspark_tpu.parallel import mesh as mesh_lib
    mesh_spec = mesh_lib.MeshSpec(data=-1, tensor=args.tensor)
  pipeline.export_bundle(
      state.params,
      tfm.make_serving_predict_fn(cfg, args.steps,
                                  temperature=args.temperature,
                                  mesh_spec=mesh_spec),
      args.export_dir)
  print("exported bundle to", args.export_dir)

  rng = np.random.RandomState(0)
  prompts = [(rng.randint(0, 256, args.prompt_len).tolist(),)
             for _ in range(args.prompts)]
  partitions = [prompts[i::args.executors] for i in range(args.executors)]

  engine = LocalEngine(num_executors=args.executors)
  try:
    model = pipeline.TFModel({"export_dir": args.export_dir,
                              "batch_size": 8})
    served = 0
    for tokens in model.transform(engine, partitions, collect=False):
      if served < 3:
        print("prompt+generation:", tokens)
      served += 1
  finally:
    engine.stop()
  print("served %d prompts x %d generated tokens each"
        % (served, args.steps))
  assert served == args.prompts
