"""Long-context Transformer training over a multi-axis mesh.

The flagship workload this framework adds beyond the reference: a
decoder-only Transformer trained with data + fsdp + sequence (ring
attention) + tensor parallelism on one jit'd train step. On a real pod the
mesh spans all chips; locally it runs on virtual CPU devices:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/transformer/train_gpt.py --dp 2 --sp 2 --tp 2
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()

if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--dp", type=int, default=-1)
  parser.add_argument("--fsdp", type=int, default=1)
  parser.add_argument("--sp", type=int, default=1)
  parser.add_argument("--tp", type=int, default=1)
  parser.add_argument("--pp", type=int, default=1,
                      help="pipeline stages: >1 trains through the 1F1B "
                           "schedule (layers split into contiguous stages)")
  parser.add_argument("--microbatches", type=int, default=4)
  parser.add_argument("--layers", type=int, default=4)
  parser.add_argument("--d_model", type=int, default=256)
  parser.add_argument("--heads", type=int, default=8)
  parser.add_argument("--seq_len", type=int, default=512)
  parser.add_argument("--vocab", type=int, default=1024)
  parser.add_argument("--batch", type=int, default=8)
  parser.add_argument("--steps", type=int, default=10)
  parser.add_argument("--blocked_loss", action="store_true",
                      help="fused projection+cross-entropy (peak memory "
                           "[B,chunk,V] instead of [B,S,V])")
  parser.add_argument("--kv_heads", type=int, default=0,
                      help="grouped-query attention: 0=MHA, 1=MQA; "
                           "grouped KV rides the ring unexpanded and the "
                           "flash kernels consume it natively")
  parser.add_argument("--fused", action="store_true",
                      help="run ln1+QKV, ln2+up and gelu+down each as "
                           "ONE Pallas kernel (fuse_qkv + ln_matmul + "
                           "act_matmul)")
  parser.add_argument("--remat_policy", default="none",
                      choices=("none", "dots"),
                      help="'dots' saves MXU outputs at remat blocks and "
                           "recomputes only elementwise work")
  parser.add_argument("--optimizer", default="adamw",
                      choices=("adamw", "lion", "adafactor", "sgd"))
  parser.add_argument("--lr", type=float, default=3e-4)
  parser.add_argument("--grad_accum", type=int, default=1,
                      help="average gradients over k steps, update once "
                           "(effective batch = k x batch)")
  parser.add_argument("--data", default=None,
                      help="TFRecord path/glob of token rows (schema "
                           "struct<tokens:array<long>>, e.g. written by "
                           "data.dfutil.save_as_tfrecords); default: "
                           "synthetic random tokens. Streams through "
                           "readers.shard_files -> shuffled -> batched "
                           "(the FILES-mode input pipeline), with one "
                           "batch always staged ahead of the step")
  parser.add_argument("--z_loss", type=float, default=0.0,
                      help="auxiliary logit stabilizer (PaLM/T5X recipe, "
                           "e.g. 1e-4); SPMD path only")
  parser.add_argument("--unroll", type=int, default=0,
                      help="fuse K optimizer steps into one dispatch "
                           "(make_train_loop lax.scan over a [K,B,S] "
                           "slab; 0 = TOS_TRAIN_UNROLL env, default "
                           "per-step); SPMD path only")
  args = parser.parse_args()

  import time

  import numpy as np
  import jax
  import jax.numpy as jnp
  from tensorflowonspark_tpu.models import transformer as tfm
  from tensorflowonspark_tpu.parallel import mesh as M
  from tensorflowonspark_tpu.parallel import sharding as SH
  from tensorflowonspark_tpu import optim

  fused = dict(fuse_qkv=True, ln_matmul_impl="fused",
               act_matmul_impl="fused") if args.fused else {}
  tx = optim.make_optimizer(learning_rate=args.lr, clip_norm=1.0,
                            optimizer=args.optimizer,
                            grad_accum_steps=args.grad_accum)

  def batch_stream():
    """[batch, seq] int32 token batches: TFRecords through the FILES-mode
    input pipeline when --data is given, else one synthetic batch."""
    if args.data:
      from tensorflowonspark_tpu.data import readers
      from tensorflowonspark_tpu.data import schema as schema_mod
      sch = schema_mod.parse_schema("struct<tokens:array<long>>")
      files = readers.shard_files(args.data, 1, 0)
      rows = readers.shuffled(
          readers.read_tfrecord_examples(files, schema=sch, repeat=True),
          buffer_size=max(64, 4 * args.batch))

      def collate(batch):
        arr = np.zeros((len(batch), args.seq_len), "int32")
        for i, r in enumerate(batch):
          t = np.asarray(r[0], "int64")[:args.seq_len]
          arr[i, :len(t)] = t
        hi = int(arr.max())
        if hi >= args.vocab:
          raise ValueError(
              "--data contains token id %d >= --vocab %d; raise --vocab "
              "to the tokenizer's size (JAX would silently clamp the "
              "embedding lookup otherwise)" % (hi, args.vocab))
        return arr

      yield from readers.batched(rows, args.batch, collate=collate)
    else:
      rng = np.random.RandomState(0)
      base = rng.randint(0, args.vocab, (args.batch, args.seq_len))
      while True:
        yield base

  def run_loop(step, state, prep):
    # one batch always staged ahead: prep (host read + async device_put /
    # shard_batch) of batch N+1 overlaps step N and stays out of the
    # timed region, so printed per-step ms measure compute, not H2D
    import collections
    stream = (prep(b) for b in batch_stream())
    buf = collections.deque()
    for i in range(args.steps):
      while len(buf) < 2:
        try:
          buf.append(next(stream))
        except StopIteration:
          break
      if not buf:
        break
      t0 = time.time()
      state, loss = step(state, buf.popleft())
      print("step %d loss %.4f (%.0f ms)"
            % (i, float(loss), 1000 * (time.time() - t0)))
    print("done; tokens/step = %d" % (args.batch * args.seq_len))

  if args.pp > 1:
    # 1F1B pipeline path: DP x PP mesh, blocks split into contiguous
    # stages, constant activation memory in the microbatch count
    if args.fsdp > 1 or args.sp > 1 or args.tp > 1 or args.blocked_loss \
        or args.z_loss:
      parser.error("--pp composes with --dp only (--fsdp/--sp/--tp/"
                   "--blocked_loss/--z_loss are the SPMD path)")
    if args.dp == -1:
      args.dp = max(1, len(jax.devices()) // args.pp)
    micro_b = args.batch // args.microbatches
    if args.batch % args.microbatches or micro_b % args.dp:
      parser.error(
          "batch %d must split into %d microbatches divisible by dp=%d "
          "(e.g. --batch %d)" % (args.batch, args.microbatches, args.dp,
                                 args.microbatches * args.dp))
    mesh = M.build_mesh(M.MeshSpec(data=args.dp, pipeline=args.pp))
    print("mesh:", dict(mesh.shape))
    cfg = tfm.TransformerConfig(
        vocab_size=args.vocab, num_layers=args.layers,
        num_heads=args.heads, d_model=args.d_model,
        d_ff=args.d_model * 4, max_seq_len=args.seq_len,
        num_kv_heads=args.kv_heads, remat_policy=args.remat_policy,
        **fused)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                             seq_len=args.seq_len, tx=tx)
    pipe = tfm.make_pipeline_train_step(cfg, mesh, args.microbatches)

    @jax.jit
    def pp_step(state, tokens):
      loss, grads = pipe(state.params, tokens)
      return state.apply_gradients(grads=grads), loss

    run_loop(pp_step, state, lambda b: jnp.asarray(b, jnp.int32))
    sys.exit(0)

  mesh = M.build_mesh(M.MeshSpec(data=args.dp, fsdp=args.fsdp,
                                 sequence=args.sp, tensor=args.tp))
  print("mesh:", dict(mesh.shape))

  cfg = tfm.TransformerConfig(
      vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
      d_model=args.d_model, d_ff=args.d_model * 4,
      max_seq_len=args.seq_len, num_kv_heads=args.kv_heads,
      remat_policy=args.remat_policy,
      use_ring_attention=mesh.shape[M.AXIS_SEQUENCE] > 1, **fused)
  state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                             mesh, seq_len=args.seq_len,
                                             tx=tx)

  def loss_fn(params, tokens):
    if args.blocked_loss:
      # fused projection+xent: never materializes [batch, seq, vocab]
      hidden = state.apply_fn({"params": params}, tokens,
                              return_hidden=True)
      return tfm.causal_lm_loss_blocked(
          hidden, tfm.tied_embedding_table(params), tokens,
          z_loss=args.z_loss)
    return tfm.causal_lm_loss(state.apply_fn({"params": params}, tokens),
                              tokens, z_loss=args.z_loss)

  unroll = SH.resolve_unroll(args.unroll or None)
  if unroll > 1:
    # fused multi-step path: K batches stacked into one Slab, K steps
    # per dispatch, the [K] loss vector fetched once per slab — same
    # trajectory as per-step (docs/PERFORMANCE.md §Train-loop fusion)
    import itertools
    from tensorflowonspark_tpu.data.readers import Slab
    loop = SH.make_train_loop(loss_fn, mesh, sharding,
                              batch_extra_axes=(M.AXIS_SEQUENCE,),
                              unroll=unroll)
    stream = batch_stream()
    while loop.steps < args.steps:
      group = [np.asarray(b, "int32") for b in
               itertools.islice(stream, min(unroll,
                                            args.steps - loop.steps))]
      if not group:
        break
      t0 = time.time()
      # a short tail group still rides the loop (per-step jit entry)
      state, losses = loop(state, Slab(np.stack(group)))
      losses = np.asarray(losses)
      print("steps %d..%d mean loss %.4f (%.0f ms, %d step(s)/dispatch)"
            % (loop.steps - len(group), loop.steps - 1, losses.mean(),
               1000 * (time.time() - t0), len(group)))
    print("done; tokens/step = %d" % (args.batch * args.seq_len))
    sys.exit(0)

  step = SH.make_train_step(loss_fn, mesh, sharding,
                            batch_extra_axes=(M.AXIS_SEQUENCE,))

  run_loop(step, state,
           lambda b: SH.shard_batch(jnp.asarray(b, jnp.int32), mesh,
                                    extra_axes=(M.AXIS_SEQUENCE,)))
