"""Long-context Transformer training over a multi-axis mesh.

The flagship workload this framework adds beyond the reference: a
decoder-only Transformer trained with data + fsdp + sequence (ring
attention) + tensor parallelism on one jit'd train step. On a real pod the
mesh spans all chips; locally it runs on virtual CPU devices:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/transformer/train_gpt.py --dp 2 --sp 2 --tp 2
"""

import argparse
import os
import sys

# allow running straight from a repo checkout (no install needed)
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, os.pardir)))

# some sandboxes register a remote-accelerator JAX plugin that hijacks even
# CPU-only runs; strip it (no-op elsewhere) so the examples run anywhere —
# real TPU hosts keep their real platform.
from tensorflowonspark_tpu.utils.platform_env import drop_remote_plugin
drop_remote_plugin()

if __name__ == "__main__":
  parser = argparse.ArgumentParser()
  parser.add_argument("--dp", type=int, default=-1)
  parser.add_argument("--fsdp", type=int, default=1)
  parser.add_argument("--sp", type=int, default=1)
  parser.add_argument("--tp", type=int, default=1)
  parser.add_argument("--layers", type=int, default=4)
  parser.add_argument("--d_model", type=int, default=256)
  parser.add_argument("--heads", type=int, default=8)
  parser.add_argument("--seq_len", type=int, default=512)
  parser.add_argument("--vocab", type=int, default=1024)
  parser.add_argument("--batch", type=int, default=8)
  parser.add_argument("--steps", type=int, default=10)
  parser.add_argument("--blocked_loss", action="store_true",
                      help="fused projection+cross-entropy (peak memory "
                           "[B,chunk,V] instead of [B,S,V])")
  args = parser.parse_args()

  import numpy as np
  import jax
  import jax.numpy as jnp
  from tensorflowonspark_tpu.models import transformer as tfm
  from tensorflowonspark_tpu.parallel import mesh as M
  from tensorflowonspark_tpu.parallel import sharding as SH

  mesh = M.build_mesh(M.MeshSpec(data=args.dp, fsdp=args.fsdp,
                                 sequence=args.sp, tensor=args.tp))
  print("mesh:", dict(mesh.shape))

  cfg = tfm.TransformerConfig(
      vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
      d_model=args.d_model, d_ff=args.d_model * 4,
      max_seq_len=args.seq_len,
      use_ring_attention=mesh.shape[M.AXIS_SEQUENCE] > 1)
  state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                             mesh, seq_len=args.seq_len)

  def loss_fn(params, tokens):
    if args.blocked_loss:
      # fused projection+xent: never materializes [batch, seq, vocab]
      hidden = state.apply_fn({"params": params}, tokens,
                              return_hidden=True)
      return tfm.causal_lm_loss_blocked(
          hidden, tfm.tied_embedding_table(params), tokens)
    return tfm.causal_lm_loss(state.apply_fn({"params": params}, tokens),
                              tokens)

  step = SH.make_train_step(loss_fn, mesh, sharding,
                            batch_extra_axes=(M.AXIS_SEQUENCE,))

  rng = np.random.RandomState(0)
  data = rng.randint(0, args.vocab, (args.batch, args.seq_len))
  tokens = SH.shard_batch(jnp.asarray(data, jnp.int32), mesh,
                          extra_axes=(M.AXIS_SEQUENCE,))

  import time
  for i in range(args.steps):
    t0 = time.time()
    state, loss = step(state, tokens)
    loss = float(loss)
    print("step %d loss %.4f (%.0f ms)" % (i, loss,
                                           1000 * (time.time() - t0)))
  print("done; tokens/step = %d" % (args.batch * args.seq_len))
